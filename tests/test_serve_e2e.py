"""serve/ end-to-end acceptance (ISSUE 4): the HTTP server over a
multi-replica process-set world, on CPU, under real concurrent load.

Pins the three acceptance properties in one scenario:

(a) batched decode output EXACTLY matches single-request decode — greedy
    decoding over a masked slot cache is batch-composition-invariant
    (engine.py module doc), so 64 concurrent requests answer identically
    to the same prompts served alone;
(b) continuous batching actually batched: /metrics reports max batch
    occupancy > 1;
(c) losing one replica's rank mid-load (a preemption marker in the same
    rendezvous-KV ``preempt`` scope the elastic driver consumes) requeues
    only that replica's in-flight work onto survivors, every response
    stays correct, and /healthz flips to degraded.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.elastic.preemption import PREEMPT_SCOPE
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer
from horovod_tpu.serve import ServeServer, TransformerAdapter, build_replicas

# Serialize with the other heavy e2e files (conftest loadgroup policy):
# this test runs 4 engines + an HTTP thread pool on the shared core.
pytestmark = pytest.mark.xdist_group("heavy_e2e")

CFG = TransformerConfig(vocab_size=89, num_layers=2, num_heads=2,
                        d_model=32, d_ff=64, max_len=96, causal=True,
                        dtype=jnp.float32, scan_layers=False)
NEW_TOKENS = 12
N_REQUESTS = 64


def _gen(port, prompt, n=NEW_TOKENS, timeout=120):
    body = json.dumps({"tokens": prompt, "max_new_tokens": n}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as resp:
        return resp.read().decode()


def _metric_value(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    raise AssertionError(f"{name} not in /metrics:\n{text}")


@pytest.mark.slow  # ~30s concurrent-load soak
def test_serving_e2e_concurrent_load_and_replica_loss(hvd8):
    model = Transformer(CFG)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    sched = build_replicas(lambda: TransformerAdapter(CFG, params),
                           num_replicas=4, max_batch=4)
    assert [r.ranks for r in sched.replicas] == \
        [[0, 1], [2, 3], [4, 5], [6, 7]]  # process-set world, >= 2 replicas

    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    kv = KVStoreServer()
    kv_port = kv.start(0)
    try:
        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, CFG.vocab_size,
                               size=(int(rng.randint(3, 24)),)).tolist()
                   for _ in range(N_REQUESTS)]
        # (a) reference pass: every distinct prompt served ALONE (the
        # engine decodes it at occupancy 1).  Also warms every prefill
        # bucket so the storm below is steady-state.
        singles = [_gen(port, p)["tokens"] for p in prompts[:8]]
        for got, p in zip(singles, prompts[:8]):
            assert len(got) == NEW_TOKENS, (got, p)

        # Preemption watcher wired to the SAME KV scope the elastic
        # driver's PreemptionAwareDiscovery consumes.
        client = KVStoreClient("127.0.0.1", kv_port)
        victim = sched.replicas[0]
        host_ranks = {"preempt-host": list(victim.ranks)}
        sched.watch_preemption(client, host_ranks, poll_s=0.05)

        # The 64-request storm.
        results = [None] * N_REQUESTS
        errors = []

        def run(i):
            try:
                results[i] = _gen(port, prompts[i])
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(N_REQUESTS)]
        for t in threads:
            t.start()
        # (c) kill one replica's rank mid-load: wait until the victim
        # demonstrably has in-flight sequences, then publish the marker.
        deadline = time.monotonic() + 60
        while victim.engine.active_count == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert victim.engine.active_count > 0, "victim never got load"
        client.put(PREEMPT_SCOPE, "preempt-host",
                   b"TERMINATE_ON_HOST_MAINTENANCE")
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors

        # (a) exactness: batched == single for every request.  All 64
        # responses must match the occupancy-1 reference for their
        # prompt — including the requeued ones.
        expected = {tuple(p): s for p, s in zip(prompts[:8], singles)}
        for p, r in zip(prompts, results):
            key = tuple(p)
            if key not in expected:
                expected[key] = _gen(port, p)["tokens"]  # fresh reference
            assert r["tokens"] == expected[key], (p, r)

        # (c) only the dead replica's work moved, onto survivors.
        requeued = [r for r in results if r["requeues"] > 0]
        assert requeued, "no in-flight requests were requeued"
        assert all(r["replica"] != victim.replica_id for r in requeued)
        health = json.loads(_get(port, "/healthz"))
        assert health["status"] == "degraded"
        assert sum(1 for r in health["replicas"]
                   if r["state"] == "dead") == 1

        # (b) the engine really batched: occupancy > 1 observed.
        metrics_text = _get(port, "/metrics")
        assert _metric_value(metrics_text,
                             "hvd_serve_batch_occupancy_max") > 1
        requeued_total = _metric_value(
            metrics_text, 'hvd_serve_requests_total{outcome="requeued"}')
        assert requeued_total == len(requeued)
        assert _metric_value(metrics_text, "hvd_serve_tokens_total") >= \
            N_REQUESTS * NEW_TOKENS
        # Latency histograms populated (TTFT + per-token).
        assert _metric_value(metrics_text, "hvd_serve_ttft_ms_count") > 0
        assert _metric_value(metrics_text,
                             "hvd_serve_token_step_ms_count") > 0
    finally:
        server.stop()
        kv.stop()


@pytest.mark.integration
def test_hvdserve_cli_starts_and_answers(tmp_path):
    """The console entry (`python -m horovod_tpu.serve`, = the hvdserve
    script target) boots a replica world and answers /generate."""
    import os
    import re
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.serve", "--model", "mlp",
         "--replicas", "2", "--port", "0", "--vocab-size", "32"],
        cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            m = re.search(r"listening on :(\d+)", line or "")
            if m:
                port = int(m.group(1))
                break
        assert port, "hvdserve never reported its port"
        out = _gen(port, [3, 4], n=4)
        assert len(out["tokens"]) == 4
        health = json.loads(_get(port, "/healthz"))
        assert health["status"] == "ok" and health["total"] == 2
    finally:
        proc.terminate()
        proc.wait(timeout=30)


def test_serving_http_surfaces(hvd8):
    """Status-code contract: 400 malformed, 404 unknown, 503 + Retry-After
    when unserving, /healthz 503 once every replica is dead."""
    model = Transformer(CFG)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    sched = build_replicas(lambda: TransformerAdapter(CFG, params),
                           num_replicas=2, max_batch=2)
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    try:
        out = _gen(port, [1, 2, 3], n=2)
        assert len(out["tokens"]) == 2 and out["ttft_ms"] is not None

        with pytest.raises(urllib.error.HTTPError) as ei:
            _gen(port, [])
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/nosuch")
        assert ei.value.code == 404

        sched.mark_dead("replica-0")
        sched.mark_dead("replica-1")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _gen(port, [1])
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") == "1"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "unserving"
    finally:
        server.stop()
