"""Hermetic test environment: 8 virtual CPU devices emulate an 8-chip slice.

This is the TPU analog of the reference running its parallel suite under
``horovodrun -np 2`` with CPU Gloo as the hermetic backend (SURVEY.md §4):
multi-chip is simulated as multi-device in one process via
``--xla_force_host_platform_device_count``, and every collective really
executes through XLA's CPU collective implementation.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("HVD_TPU_EMULATE_RANKS", "8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(items):
    """xdist scheduling policy (--dist loadgroup, pyproject addopts).

    Subprocess-world e2e tests (multi-process jax + gloo + rendezvous)
    thrash each other when they overlap on this box's single host core —
    cascading spurious stall timeouts and elastic resets.  Files that
    spawn such worlds declare ``pytestmark = pytest.mark.xdist_group
    ("heavy_e2e")`` so they all serialize on ONE xdist worker; every
    unmarked test inherits its module as its group, preserving the
    per-file serialization of plain --dist loadfile for the light
    in-process tests."""
    for item in items:
        if not any(m.name == "xdist_group" for m in item.iter_markers()):
            item.add_marker(
                pytest.mark.xdist_group(item.module.__name__))


@pytest.fixture()
def hvd8():
    """Initialized runtime with 8 emulated ranks; torn down after the test."""
    import horovod_tpu as hvd
    hvd.shutdown()
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture(scope="session", autouse=True)
def _lock_witness_session():
    """HVD_SANITIZE=1 runs the whole suite under the lock-witness
    sanitizer (analysis/witness.py): locks constructed during the run are
    order-checked live, and the session FAILS at teardown on any
    inversion/naked-wait finding left standing (tests that seed
    violations deliberately reset the witness themselves).  A no-op (one
    env read) when the env is unset."""
    from horovod_tpu.analysis import witness
    installed = witness.maybe_install_from_env()
    yield
    if installed:
        findings = witness.findings()
        witness.uninstall()
        assert not findings, (
            "HVD_SANITIZE: the suite left lock-witness findings "
            "standing:\n" + "\n".join(f.format() for f in findings))
