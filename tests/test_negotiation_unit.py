"""In-process negotiation protocol tests: two Negotiator endpoints (threads)
over a local KV store — fast coverage of the coordinator/worker contract
without spawning worker processes."""

import os
import threading

import pytest

from horovod_tpu.config import Config
from horovod_tpu.exceptions import HorovodInternalError
from horovod_tpu.ops.negotiation import Negotiator
from horovod_tpu.runner.http_server import KVStoreServer


@pytest.fixture()
def kv_env(monkeypatch):
    srv = KVStoreServer()
    port = srv.start()
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
    monkeypatch.setenv("HOROVOD_GLOO_TIMEOUT_SECONDS", "20")
    yield srv
    srv.stop()


def _pair(kv_env):
    cfg = Config.from_env()
    return Negotiator(0, 2, cfg), Negotiator(1, 2, cfg)


def _negotiate_both(n0, n1, sig0, sig1):
    errs = [None, None]

    def go(i, n, sig):
        try:
            n.negotiate(*sig)
        except Exception as e:
            errs[i] = e

    t0 = threading.Thread(target=go, args=(0, n0, sig0))
    t1 = threading.Thread(target=go, args=(1, n1, sig1))
    t0.start(); t1.start()
    t0.join(timeout=30); t1.join(timeout=30)
    return errs


def test_matching_signatures_pass(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("t", "allreduce", "float32", (4,), 1),
        ("t", "allreduce", "float32", (4,), 1))
    assert errs == [None, None]
    # Second round: cache HIT on both sides (no traffic, returns instantly)
    n0.negotiate("t", "allreduce", "float32", (4,), 1)
    n1.negotiate("t", "allreduce", "float32", (4,), 1)


def test_shape_mismatch_rejected_on_both(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("u", "allreduce", "float32", (4,), 1),
        ("u", "allreduce", "float32", (5,), 1))
    assert all(isinstance(e, HorovodInternalError) for e in errs)
    assert "Mismatched shapes" in str(errs[0])


def test_op_mismatch_rejected(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("v", "allreduce", "float32", (4,), 1),   # Sum
        ("v", "allreduce", "float32", (4,), 0))   # Average
    assert all(isinstance(e, HorovodInternalError) for e in errs)
    assert "Mismatched ops" in str(errs[0])


def test_shape_change_renegotiates_with_invalidation(kv_env):
    n0, n1 = _pair(kv_env)
    assert _negotiate_both(n0, n1, ("w", "allreduce", "float32", (4,), 1),
                           ("w", "allreduce", "float32", (4,), 1)) == \
        [None, None]
    # Both change shape: INVALID -> fresh epoch -> succeeds again.
    assert _negotiate_both(n0, n1, ("w", "allreduce", "float32", (8,), 1),
                           ("w", "allreduce", "float32", (8,), 1)) == \
        [None, None]


def test_ps_id_mismatch_rejected(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("x", "allreduce", "float32", (4,), 1, 1.0, 1.0, 1),
        ("x", "allreduce", "float32", (4,), 1, 1.0, 1.0, 2))
    assert any(isinstance(e, HorovodInternalError) for e in errs)


def test_kv_long_poll_blocks_until_put():
    """GET ?wait=s must hold until the key appears (no 404 race) and a
    late key must still 404 after the wait elapses."""
    import threading
    import time as _time
    from horovod_tpu.runner.http_server import KVStoreServer, KVStoreClient
    srv = KVStoreServer()
    port = srv.start(0)
    try:
        c = KVStoreClient("127.0.0.1", port)
        # times out -> None
        t0 = _time.perf_counter()
        assert c.get("s", "never", wait=0.2) is None
        assert _time.perf_counter() - t0 >= 0.18
        # concurrent put releases the waiter with the value
        def put_later():
            _time.sleep(0.15)
            srv.put("s", "k", b"v1")
        th = threading.Thread(target=put_later)
        th.start()
        t0 = _time.perf_counter()
        assert c.get("s", "k", wait=5.0) == b"v1"
        assert _time.perf_counter() - t0 < 4.0
        th.join()
    finally:
        srv.stop()


@pytest.mark.slow  # ~7s scale smoke
def test_control_plane_scale_smoke():
    """Regression guard for the round-3 control-plane fixes (Nagle stall,
    polling saturation).  Budgets are loose — this box has ONE core shared
    by all workers and the server — but they sit far below the broken
    behavior (new-sig p50 was 400+ ms at np=8 pre-fix, cached p50 64 ms
    at np=16)."""
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    from control_plane_bench import run_scale
    row = run_scale(4, names=10, repeats=5)
    assert row["new_p50_ms"] < 150, row
    assert row["hit_p50_ms"] < 25, row
