"""In-process negotiation protocol tests: two Negotiator endpoints (threads)
over a local KV store — fast coverage of the coordinator/worker contract
without spawning worker processes."""

import threading

import pytest

from horovod_tpu.config import Config
from horovod_tpu.exceptions import HorovodInternalError
from horovod_tpu.ops.negotiation import Negotiator
from horovod_tpu.runner.http_server import KVStoreServer


@pytest.fixture()
def kv_env(monkeypatch):
    srv = KVStoreServer()
    port = srv.start()
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
    monkeypatch.setenv("HOROVOD_GLOO_TIMEOUT_SECONDS", "20")
    yield srv
    srv.stop()


def _pair(kv_env):
    cfg = Config.from_env()
    return Negotiator(0, 2, cfg), Negotiator(1, 2, cfg)


def _negotiate_both(n0, n1, sig0, sig1):
    errs = [None, None]

    def go(i, n, sig):
        try:
            n.negotiate(*sig)
        except Exception as e:
            errs[i] = e

    t0 = threading.Thread(target=go, args=(0, n0, sig0))
    t1 = threading.Thread(target=go, args=(1, n1, sig1))
    t0.start(); t1.start()
    t0.join(timeout=30); t1.join(timeout=30)
    return errs


def test_matching_signatures_pass(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("t", "allreduce", "float32", (4,), 1),
        ("t", "allreduce", "float32", (4,), 1))
    assert errs == [None, None]
    # Second round: cache HIT on both sides (no traffic, returns instantly)
    n0.negotiate("t", "allreduce", "float32", (4,), 1)
    n1.negotiate("t", "allreduce", "float32", (4,), 1)


def test_shape_mismatch_rejected_on_both(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("u", "allreduce", "float32", (4,), 1),
        ("u", "allreduce", "float32", (5,), 1))
    assert all(isinstance(e, HorovodInternalError) for e in errs)
    assert "Mismatched shapes" in str(errs[0])


def test_op_mismatch_rejected(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("v", "allreduce", "float32", (4,), 1),   # Sum
        ("v", "allreduce", "float32", (4,), 0))   # Average
    assert all(isinstance(e, HorovodInternalError) for e in errs)
    assert "Mismatched ops" in str(errs[0])


def test_shape_change_renegotiates_with_invalidation(kv_env):
    n0, n1 = _pair(kv_env)
    assert _negotiate_both(n0, n1, ("w", "allreduce", "float32", (4,), 1),
                           ("w", "allreduce", "float32", (4,), 1)) == \
        [None, None]
    # Both change shape: INVALID -> fresh epoch -> succeeds again.
    assert _negotiate_both(n0, n1, ("w", "allreduce", "float32", (8,), 1),
                           ("w", "allreduce", "float32", (8,), 1)) == \
        [None, None]


def test_ps_id_mismatch_rejected(kv_env):
    n0, n1 = _pair(kv_env)
    errs = _negotiate_both(
        n0, n1,
        ("x", "allreduce", "float32", (4,), 1, 1.0, 1.0, 1),
        ("x", "allreduce", "float32", (4,), 1, 1.0, 1.0, 2))
    assert any(isinstance(e, HorovodInternalError) for e in errs)
