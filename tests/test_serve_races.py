"""Concurrency pinning for the serve fleet: the HVD_SANITIZE=1 stress
test plus the thread-lifecycle stop-path contracts.

The stress test hammers ``ReplicaScheduler.submit`` / ``mark_dead`` /
``mark_alive`` / a ``/metrics``-style render loop / the batcher's
deadline-expiry path concurrently for a couple of seconds with the
lock-witness sanitizer (analysis/witness.py) installed, and asserts ZERO
witness findings — pinning the PR 3 batcher-lock/metrics-lock AB/BA
deadlock class forever: if anyone reintroduces a lock nesting between
those components in either direction, the witness sees the inversion the
first time both paths run.

The stop-path tests pin the HVD203 contract on the repo's own long-lived
threads: ``ServeServer.stop`` / ``KVStoreServer.stop`` join their
serve_forever acceptors, ``ElasticDriver.stop`` joins the discovery
loop, and ``Negotiator.close`` joins the dispatch flusher — no stop path
leaves a thread behind (daemon remains the interpreter-exit backstop for
genuinely wedged I/O).
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu.analysis import witness
from horovod_tpu.models import create_mlp
from horovod_tpu.serve import (DynamicBatcher, InferenceEngine, MLPAdapter,
                               QueueFullError, Replica, ReplicaScheduler,
                               Request, ServeMetrics, ServeServer)

VOCAB = 17


def _mlp_adapter(seed=3, vocab=VOCAB, max_len=64):
    mlp = create_mlp(features=(8, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return MLPAdapter(mlp, params, vocab_size=vocab, max_len=max_len)


def _fleet(metrics, n=2, max_batch=4):
    replicas = []
    for i in range(n):
        rid = f"replica-{i}"
        eng = InferenceEngine(_mlp_adapter(seed=i + 1),
                              batcher=DynamicBatcher(max_queue=64),
                              metrics=metrics, max_batch=max_batch,
                              replica_id=rid)
        replicas.append(Replica(rid, None, eng))
    return ReplicaScheduler(replicas, metrics=metrics)


def test_serve_fleet_stress_zero_witness_findings(monkeypatch):
    """A few seconds of submit/mark_dead/mark_alive/render/deadline-expiry
    chaos under HVD_SANITIZE=1: the fleet must hold a single consistent
    lock order (zero HVD210/HVD211 findings)."""
    monkeypatch.setenv("HVD_SANITIZE", "1")
    was_installed = witness.installed()
    assert witness.maybe_install_from_env()
    witness.reset()
    scheduler = None
    try:
        # Everything constructed AFTER install: every fleet lock is
        # witness-wrapped.
        metrics = ServeMetrics()
        scheduler = _fleet(metrics)
        scheduler.start()
        stop = threading.Event()
        errors = []
        done = []

        def storm():
            i = 0
            while not stop.is_set():
                i += 1
                r = Request([1 + i % (VOCAB - 2)], max_new_tokens=2)
                try:
                    scheduler.submit(r)
                except QueueFullError:
                    time.sleep(0.002)
                    continue
                except Exception as e:  # no-survivor windows are a bug
                    errors.append(e)
                    return
                done.append(r)
                time.sleep(0.001)

        def expiry_storm():
            # Tiny budgets: these die in the queue, driving the batcher's
            # _pop_expired + on_shed path (the PR 3 half-A) while the
            # render loop (half-B) runs concurrently.
            while not stop.is_set():
                r = Request([1], max_new_tokens=2, timeout_s=0.004)
                try:
                    scheduler.submit(r)
                except Exception:
                    pass
                time.sleep(0.002)

        def scrape():
            while not stop.is_set():
                metrics.render()
                metrics.snapshot()
                scheduler.healthz()
                time.sleep(0.002)

        def flapper():
            while not stop.is_set():
                scheduler.mark_dead("replica-0", reason="stress flap")
                time.sleep(0.05)
                scheduler.mark_alive("replica-0", reason="stress flap")
                time.sleep(0.05)

        threads = [threading.Thread(target=fn, daemon=True)
                   for fn in (storm, expiry_storm, scrape, flapper)]
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert not errors, errors
        # The fleet really worked: requests flowed and the expiry path
        # really fired (the stress is vacuous otherwise).
        assert len(done) > 10
        snap = metrics.snapshot()
        assert snap["requests"].get("expired", 0) > 0
        assert snap["replica_events"]["mark_dead"] >= 1
        assert snap["replica_events"]["mark_alive"] >= 1
        # THE assertion: zero lock-order inversions, zero naked waits.
        findings = witness.findings()
        assert not findings, "\n".join(f.format() for f in findings)
    finally:
        if scheduler is not None:
            scheduler.stop()
        witness.reset()
        if not was_installed:
            witness.uninstall()


# ---------------------------------------------------------------------------
# Stop-path thread lifecycle (the HVD203 contract on the repo's threads)
# ---------------------------------------------------------------------------

def test_serve_server_stop_joins_listener():
    scheduler = _fleet(ServeMetrics(), n=1)
    server = ServeServer(scheduler, request_timeout_s=5)
    server.start(port=0, host="127.0.0.1")
    listener = server._thread
    assert listener is not None and listener.is_alive()
    server.stop()
    assert not listener.is_alive()
    assert server._thread is None


def test_kvstore_server_stop_joins_acceptor(monkeypatch):
    from horovod_tpu.runner.http_server import KVStoreServer
    monkeypatch.setenv("HVD_TPU_KV_SERVER", "python")
    srv = KVStoreServer()
    srv.start()
    acceptor = srv._thread
    assert acceptor is not None and acceptor.is_alive()
    srv.stop()
    assert not acceptor.is_alive()
    # Store stays readable after stop (module-doc contract).
    srv.put("s", "k", b"v")
    assert srv.get("s", "k") == b"v"


def test_elastic_driver_stop_joins_discovery_thread(monkeypatch):
    from horovod_tpu.elastic.discovery import HostDiscovery
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.http_server import RendezvousServer

    class _FixedDiscovery(HostDiscovery):
        def find_available_hosts_and_slots(self):
            return {"localhost": 2}

    monkeypatch.setenv("HVD_TPU_KV_SERVER", "python")
    rendezvous = RendezvousServer()
    rendezvous.start()
    try:
        driver = ElasticDriver(rendezvous, _FixedDiscovery(),
                               min_np=1, max_np=2, timeout=10)
        # Start ONLY the discovery loop (instant-exit worker bodies —
        # the full launch path is test_elastic's job); stop() must join
        # the loop deterministically.
        driver._worker_cmd_fn = lambda slot, ev, version: 0
        driver._discovery_thread.start()
        time.sleep(0.2)
        assert driver._discovery_thread.is_alive()
        driver.stop()
        assert not driver._discovery_thread.is_alive()
        # stop() before start() is a no-op on the (unstarted) thread.
        driver2 = ElasticDriver(rendezvous, _FixedDiscovery(),
                                min_np=1, max_np=2, timeout=10)
        driver2.stop()
        assert not driver2._discovery_thread.is_alive()
    finally:
        rendezvous.stop()


def test_negotiator_close_joins_flusher(monkeypatch):
    from horovod_tpu.config import Config
    from horovod_tpu.ops.negotiation import Negotiator
    from horovod_tpu.runner.http_server import KVStoreServer

    monkeypatch.setenv("HVD_TPU_KV_SERVER", "python")
    srv = KVStoreServer()
    port = srv.start()
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
    try:
        n = Negotiator(0, 2, Config.from_env())
        assert n.enabled
        n.publish_dispatch("t", 0, {"dtype": "float32", "shape": [4],
                                    "op": 1}, "allreduce")
        flusher = n._flusher
        assert flusher is not None and flusher.is_alive()
        n.close()
        flusher.join(timeout=5)  # close() already joined; belt for CI
        assert not flusher.is_alive()
        # The pending record was shipped, not stranded.
        assert n.poll_dispatch(0, 1) is not None
    finally:
        srv.stop()
