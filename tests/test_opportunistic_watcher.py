"""tools/opportunistic_capture.sh success path (VERDICT r4 #9).

The watcher's job: the moment a relay probe succeeds, run the bench
battery and exit 0 iff the driver-default invocation produced a FRESH
capture (the last stdout JSON line is non-stale — bench.py's emit-first
fallback prints a stale line on every run, so "any stale marker in the
output" stopped being a usable signal in round 5).

These tests run the REAL script in an isolated repo-shaped temp dir with
a `python` shim on PATH: the probe succeeds instantly and bench.py is
stubbed per scenario, so a 30-second relay blip converting into a
persisted capture is exercised end-to-end without hardware.
"""

import os
import shutil
import stat
import subprocess

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SHIM = """#!/bin/bash
# python shim: succeed the probe, emulate bench.py per BENCH_STUB, and
# delegate everything else (the watcher's own last-JSON-line checker runs
# `python - file`) to the real interpreter.
for a in "$@"; do
  case "$a" in
    bench.py)
      echo '{"metric": "resnet50_synthetic_images_per_sec", "value": 1995.0, "stale": true, "stale_reason": "emit-first"}'
      if [ "${BENCH_STUB}" = "fresh" ]; then
        echo '{"metric": "resnet50_synthetic_images_per_sec", "value": 2700.0, "unit": "images/sec"}'
      fi
      exit 0
      ;;
  esac
done
if [ "${1:-}" = "-c" ]; then
  exit 0  # the probe: import jax; assert jax.devices()
fi
exec "$REAL_PYTHON" "$@"
"""


@pytest.fixture()
def watcher_dir(tmp_path):
    """Repo-shaped sandbox: tools/opportunistic_capture.sh + artifacts/ +
    a PATH shim standing in for python."""
    (tmp_path / "tools").mkdir()
    (tmp_path / "artifacts").mkdir()
    shutil.copy(os.path.join(_REPO, "tools", "opportunistic_capture.sh"),
                tmp_path / "tools" / "opportunistic_capture.sh")
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "python"
    shim.write_text(_SHIM)
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    return tmp_path


def _run(watcher_dir, stub):
    import sys
    env = dict(os.environ,
               PATH=f"{watcher_dir / 'bin'}:{os.environ['PATH']}",
               OPP_TEST_MODE="1", OPP_LOOP_ONCE="1", BENCH_STUB=stub,
               REAL_PYTHON=sys.executable)
    return subprocess.run(
        ["bash", str(watcher_dir / "tools" / "opportunistic_capture.sh")],
        env=env, capture_output=True, text=True, timeout=120)


def test_watcher_exits_success_on_fresh_capture(watcher_dir):
    r = _run(watcher_dir, stub="fresh")
    assert r.returncode == 0, (r.stdout, r.stderr)
    log = (watcher_dir / "artifacts" /
           "opportunistic_capture.log").read_text()
    assert "relay up" in log
    assert "capture complete; watcher exiting" in log
    out = (watcher_dir / "artifacts" /
           "capture_resnet_fast.out").read_text()
    assert '"value": 2700.0' in out  # the fresh line reached the record


def test_watcher_keeps_looping_on_stale_only_output(watcher_dir):
    """bench exiting 0 with only the emit-first stale line is NOT a
    capture: the success check keys on the LAST JSON line being
    non-stale (a plain stale-marker grep would deadlock the watcher
    forever after round 5's emit-first rework)."""
    r = _run(watcher_dir, stub="stale_only")
    assert r.returncode == 3, (r.stdout, r.stderr)  # looped, no success
    log = (watcher_dir / "artifacts" /
           "opportunistic_capture.log").read_text()
    assert "capture complete" not in log
    assert "rc=(99," in log  # stale emission classified, not mistaken
