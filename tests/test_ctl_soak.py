"""hvdctl diurnal-load soak (ISSUE 13 acceptance, ``slow``): a seeded
low -> peak -> low load sweep with a faultline replica kill at peak.

The controller must scale UP through the kill (reviving dead spares),
hit the envelope, walk the brownout ladder (shedding ONLY the
throughput tier — latency-tier requests all complete, bit-identical to
their single-served references, inside the SLO), then walk the ladder
back to 0 and scale DOWN once the load recedes.

The load shape is ``faultline.diurnal_load`` (a pure function of its
seed) and the kill is a seeded ``kill-rank`` spec at the routing point,
so the whole storm replays identically from the same seeds.  The
controller's poll loop is driven MANUALLY (``FleetController.poll`` is
public exactly for this) — actions happen at known points between load
ticks instead of racing a background thread's clock.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import faultline as fl
from horovod_tpu.analysis import witness
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.serve import (ControllerConfig, FleetController,
                               QueueFullError, Request, ServeServer,
                               TransformerAdapter, build_replicas)

pytestmark = [pytest.mark.slow, pytest.mark.xdist_group("heavy_e2e")]

CFG = TransformerConfig(vocab_size=89, num_layers=2, num_heads=2,
                        d_model=32, d_ff=64, max_len=96, causal=True,
                        dtype=jnp.float32, scan_layers=False)
NEW_TOKENS = 16
LOAD_SEED = 21
FAULT_SEED = 4321
SLO_MS = 15_000.0  # latency-tier p99 ceiling on a loaded CPU CI box


def _gen(port, prompt, qos="latency", n=NEW_TOKENS, timeout=180):
    body = json.dumps({"tokens": prompt, "max_new_tokens": n,
                       "qos": qos}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _ctl_cfg():
    """Fast-reacting envelope for the soak: 3 of the 4 built replicas
    may serve (max_replicas=3 < fleet size), so sustained peak pressure
    EXHAUSTS the envelope and must brown out; ``brownout_max_new`` is
    kept >= NEW_TOKENS so the rung-2 cap never truncates a response
    (bit-identity is part of the acceptance)."""
    return ControllerConfig(
        poll_s=0.05, min_replicas=1, max_replicas=3,
        queue_high=2.0, queue_low=1.0, up_polls=2, down_polls=2,
        up_cooldown_s=0.0, down_cooldown_s=0.0,
        brownout_polls=1, brownout_clear_polls=2,
        brownout_max_new=NEW_TOKENS).validate()


def test_diurnal_soak_scales_through_kill_and_sheds_only_throughput(hvd8):
    model = Transformer(CFG)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    sched = build_replicas(lambda: TransformerAdapter(CFG, params),
                           num_replicas=4, max_batch=4)
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")

    injected = []

    def load_injector(burst):
        # faultline load-spike sink: synthetic throughput-tier work
        # straight into the scheduler (no HTTP client attached).  Under
        # brownout rung >= 1 the batchers shed it — that IS the rung
        # doing its job, not an injection failure.
        ok = 0
        for i in range(burst):
            try:
                sched.submit(Request([1 + i % 8, 2, 3], max_new_tokens=4,
                                     qos="throughput"))
                ok += 1
            except QueueFullError:
                pass
        injected.append(ok)
        return ok

    ctl = FleetController(sched, config=_ctl_cfg(),
                          load_injector=load_injector)
    try:
        rng = np.random.RandomState(13)
        prompts = [rng.randint(0, CFG.vocab_size,
                               size=(int(rng.randint(3, 24)),)).tolist()
                   for _ in range(48)]
        # Load-free reference pass: every prompt single-served (also
        # warms the prefill buckets).  10 submits -> the kill step below
        # must land beyond them.
        singles = {tuple(p): _gen(port, p)["tokens"] for p in prompts[:10]}

        # Two spares down: the diurnal trough needs only 2 replicas, and
        # scale-up has something to revive.  An IDLE mark_dead requeues
        # nothing (tests/test_serve_paged.py pins the refund).
        sched.mark_dead("replica-2", reason="soak setup: spare")
        sched.mark_dead("replica-3", reason="soak setup: spare")

        plan = fl.install(fl.FaultPlan([
            # Mid-burst routing-time kill of an originally-healthy
            # replica: route counter passes 20 early in the peak storm
            # (10 reference + 5 trough submits precede it).
            fl.FaultSpec("kill-rank", point="replica.route",
                         target="replica-0", step=20),
            # A seeded synthetic overload burst at the controller's own
            # poll point, on top of the organic peak.
            fl.FaultSpec("load-spike", step=6, repeat=2, param=6.0),
        ], seed=FAULT_SEED))
        assert plan.schedule() == fl.FaultPlan(
            [fl.FaultSpec("kill-rank", point="replica.route",
                          target="replica-0", step=20),
             fl.FaultSpec("load-spike", step=6, repeat=2, param=6.0)],
            seed=FAULT_SEED).schedule()

        shape = fl.diurnal_load(12, peak=10, base=1, seed=LOAD_SEED)
        assert shape == fl.diurnal_load(12, peak=10, base=1,
                                        seed=LOAD_SEED)  # replayable

        # -- trough (ticks 0-1): sparse sequential traffic, idle polls --
        p_i = 0
        for tick in range(2):
            for _ in range(shape[tick]):
                p = prompts[p_i % 10]  # trough prompts are all warmed
                assert _gen(port, p)["tokens"] == singles[tuple(p)]
                p_i += 1
            ctl.poll()

        # -- peak: the remaining shape fired as one concurrent storm ----
        storm = []
        for tick in range(2, len(shape)):
            for j in range(shape[tick]):
                qos = "throughput" if (p_i + j) % 3 == 0 else "latency"
                storm.append((prompts[(p_i + j) % len(prompts)], qos))
            p_i += shape[tick]
        lat_results = {}
        tpt_outcomes = []
        errors = []

        def run(i, prompt, qos):
            try:
                out = _gen(port, prompt, qos=qos)
                if qos == "latency":
                    lat_results[i] = (prompt, out)
                else:
                    tpt_outcomes.append("ok")
            except urllib.error.HTTPError as e:
                if qos == "throughput" and e.code == 503:
                    tpt_outcomes.append("shed")  # brownout doing its job
                else:
                    errors.append((i, qos, repr(e)))
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append((i, qos, repr(e)))

        threads = [threading.Thread(target=run, args=(i, p, q))
                   for i, (p, q) in enumerate(storm)]
        for t in threads:
            t.start()
        # Drive the controller through the storm; record the rung walk.
        max_level = 0
        deadline = time.monotonic() + 180
        while any(t.is_alive() for t in threads) \
                and time.monotonic() < deadline:
            ctl.poll()
            max_level = max(max_level, ctl.stats()["brownout_level"])
            if max_level >= 1:
                # Deterministic tier check AT a browned-out instant
                # (polls are manual, the rung cannot move under us):
                # throughput is shed with 503, latency still admits.
                if not getattr(run, "_probed", False):
                    run._probed = True
                    with pytest.raises(urllib.error.HTTPError) as ei:
                        _gen(port, prompts[0], qos="throughput", n=2)
                    assert ei.value.code == 503
                    assert "brownout" in json.loads(
                        ei.value.read())["error"]
                    probe = _gen(port, prompts[0], qos="latency")
                    assert probe["tokens"] == singles[tuple(prompts[0])]
            time.sleep(0.03)
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors

        # The fleet scaled up THROUGH the kill: the routed kill fired,
        # and revives outnumber it (spares came back under pressure).
        assert plan.exhausted(), plan.schedule()
        assert {k for _, _, k in plan.firing_sequence()} == \
            {"kill-rank", "load-spike"}
        assert ctl.stats()["scale_events"]["scale_up"] >= 1
        assert max_level >= 1, "peak never exhausted the envelope"
        assert ctl.stats()["brownout_seconds"] > 0.0

        # -- recede: idle polls walk the ladder down, then shrink -------
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            ctl.poll()
            s = ctl.stats()
            if s["brownout_level"] == 0 and \
                    s["scale_events"]["scale_down"] >= 1:
                break
            time.sleep(0.02)
        s = ctl.stats()
        assert s["brownout_level"] == 0, s
        assert s["scale_events"]["brownout_down"] >= 1
        assert s["scale_events"]["scale_down"] >= 1, s
        for r in sched.fleet():
            assert r.engine.batcher.brownout_level == 0
            assert r.engine.batcher.brownout_max_new == 0

        # ONLY the throughput tier was shed: every latency-tier request
        # completed, bit-identical to its single-served reference.
        assert lat_results, "storm had no latency-tier requests"
        for prompt, out in lat_results.values():
            key = tuple(prompt)
            if key not in singles:
                singles[key] = _gen(port, prompt)["tokens"]
            assert out["tokens"] == singles[key], (prompt, out)
            assert out["qos"] == "latency"

        # Latency-tier p99 held the SLO across the whole window.
        snap = sched.metrics.snapshot()
        lat_hist = snap["request_latency"]["latency"]
        assert lat_hist["count"] >= len(lat_results)
        assert lat_hist["p99_ms"] <= SLO_MS, lat_hist
        assert snap["brownout_level"] == 0
        assert snap["ctl_events"]["brownout_up"] >= 1
        assert snap["ctl_events"]["scale_up"] >= 1

        # Lock-witness discipline (HVD_SANITIZE=1 runs): the controller
        # plane added no ordering or held-lock findings.
        if witness.installed():
            assert witness.findings() == [], witness.findings()
    finally:
        fl.uninstall()
        ctl.stop()
        server.stop()
