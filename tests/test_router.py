"""ISSUE 18: hvdroute — fault-tolerant prefix-affinity front door.

Pins the router's contracts without sockets (``Router._transport`` is
the monkeypatch seam) plus the HTTP-layer satellites over real
listeners:

* consistent-hash ring — insertion-order independent, distinct
  preference order, removal only remaps the removed endpoint's keys;
* affinity key — fixed-depth chain hash stays stable as a session's
  transcript grows append-only; model salt separates fleets;
* bounded-load / brownout power-of-two fallback;
* passive health — consecutive-failure ejection, half-open probe,
  readmission, and the no-candidate probe-window wait (zero-lost);
* deadline-bounded retries — 502 on retry exhaustion, 504 on budget
  exhaustion, 503 honored as backpressure with Retry-After clamped to
  the remaining client budget on pass-through;
* tail hedging — slow primary raced against the next candidate, first
  definitive winner used;
* faultline — ``drop-route`` / ``slow-route`` / ``blackhole-endpoint``
  / ``kill-rank`` at ``router.forward``, including ejection counters
  reconciling with the backend scheduler's ``replica_events`` during a
  concurrent scale-down (the ISSUE 18 chaos satellite);
* drain — ServeServer and RouterServer refuse new work with 503 +
  ``Connection: close`` while in-flight requests finish, and the
  drain-refusal Retry-After is clamped by the header-borne client
  budget even though no Request object exists yet (the ISSUE 18 clamp
  satellite).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from horovod_tpu import faultline as fl
from horovod_tpu.models import create_mlp
from horovod_tpu.serve import (MLPAdapter, Router, RouterConfig,
                               RouterServer, ServeMetrics, ServeServer,
                               build_replicas)
from horovod_tpu.serve.router import _HashRing

VOCAB = 31

EP0, EP1 = "10.0.0.1:8000", "10.0.0.2:8000"

_OK_BODY = json.dumps({"tokens": [1, 2, 3]}).encode()


def _mlp_adapter(seed=3):
    mlp = create_mlp(features=(16, VOCAB))
    params = mlp.init(jax.random.PRNGKey(seed),
                      np.zeros((1, VOCAB), np.float32))["params"]
    return MLPAdapter(mlp, params, vocab_size=VOCAB, max_len=128)


def _fast_config(**overrides):
    base = dict(retry_base_s=0.001, retry_cap_s=0.005, probe_s=0.05,
                eject_failures=2, block_tokens=4)
    base.update(overrides)
    return RouterConfig(**base)


def _stub(router, behavior, calls=None):
    """Replace the transport seam: ``behavior[name]`` is a response
    tuple, an Exception to raise, or a callable returning either."""
    calls = [] if calls is None else calls

    def transport(host, port, method, path, body, headers, timeout_s):
        name = f"{host}:{port}"
        calls.append(name)
        out = behavior[name]
        if callable(out):
            out = out()
        if isinstance(out, Exception):
            raise out
        return out

    router._transport = transport
    return calls


def _key_for(router, target, want_second=None):
    """A token prompt whose ring preference order starts at ``target``
    (and, optionally, whose failover candidate is ``want_second``)."""
    for s in range(4096):
        p = [(7 * s + j) % VOCAB for j in range(12)]
        order = router._ring.lookup(router.affinity_key(p))
        if order[0] == target and \
                (want_second is None or order[1] == want_second):
            return p
    raise AssertionError(f"no prompt routes to {target}")


def _body(tokens, **extra):
    return json.dumps(dict({"tokens": tokens}, **extra)).encode()


# ---------------------------------------------------------------------------
# ring + affinity key
# ---------------------------------------------------------------------------

def test_ring_order_independent_and_distinct():
    names = [f"10.0.0.{i}:80" for i in range(5)]
    a, b = _HashRing(vnodes=32), _HashRing(vnodes=32)
    for n in names:
        a.add(n)
    for n in reversed(names):
        b.add(n)
    for key in range(50):
        assert a.lookup(key) == b.lookup(key)
        order = a.lookup(key)
        assert sorted(order) == sorted(names)  # all endpoints, no dups


def test_ring_removal_only_remaps_victims_keys():
    names = [f"10.0.0.{i}:80" for i in range(5)]
    ring = _HashRing(vnodes=32)
    for n in names:
        ring.add(n)
    before = {key: ring.lookup(key)[0] for key in range(200)}
    ring.remove(names[2])
    for key, first in before.items():
        if first == names[2]:
            assert ring.lookup(key)[0] != names[2]
        else:
            assert ring.lookup(key)[0] == first  # undisturbed


def test_affinity_key_stable_as_transcript_grows():
    r = Router([EP0, EP1], config=_fast_config(affinity_blocks=2))
    tokens = list(range(1, 13))  # 3 full 4-token blocks
    key = r.affinity_key(tokens)
    # Append-only growth (multi-turn session): key must not move.
    assert r.affinity_key(tokens + [5, 6, 7, 8, 9]) == key
    # A different leading block is a different session.
    assert r.affinity_key([9] + tokens[1:]) != key
    # Model salt separates fleets sharing a router.
    assert r.affinity_key(tokens, model="m1") != key
    # Sub-block prompts still key deterministically.
    assert r.affinity_key([1, 2]) == r.affinity_key([1, 2])


def test_bounded_load_and_brownout_fallback():
    r = Router([EP0, EP1], config=_fast_config(bounded_load=2.0))
    p = _key_for(r, EP0, want_second=EP1)
    key = r.affinity_key(p)
    affinity, avail = r._candidates(key)
    assert affinity == EP0 and avail[0] == EP0
    # Hot affinity target: power-of-two falls back to the next candidate.
    r._endpoints[EP0].inflight = 10
    _, avail = r._candidates(key)
    assert avail[0] == EP1
    # Browned-out target is treated as hot even when idle.
    r._endpoints[EP0].inflight = 0
    r._endpoints[EP0].brownout_level = 1
    _, avail = r._candidates(key)
    assert avail[0] == EP1


# ---------------------------------------------------------------------------
# retries / health / backpressure / hedging (stubbed transport)
# ---------------------------------------------------------------------------

def test_failover_ejection_half_open_readmission():
    r = Router([EP0, EP1], config=_fast_config())
    behavior = {EP0: ConnectionError("down"), EP1: (200, {}, _OK_BODY)}
    calls = _stub(r, behavior)
    body = _body(_key_for(r, EP0, want_second=EP1))
    # Two failed attempts at EP0 (eject_failures=2) → ejected; both
    # requests still answer from EP1 (zero lost).
    for _ in range(2):
        status, _, out = r.handle(body, {})
        assert status == 200 and out == _OK_BODY
    snap = r.metrics.snapshot()
    assert snap["ejections"] == 1 and snap["retries"] >= 2
    assert not r._endpoints[EP0].admitted
    # While ejected (inside the probe window) EP0 is never routed to.
    calls.clear()
    status, _, _ = r.handle(body, {})
    assert status == 200 and EP0 not in calls
    # Probe window opens, the endpoint recovers: one half-open probe
    # readmits it.
    behavior[EP0] = (200, {}, _OK_BODY)
    time.sleep(r.config.probe_s + 0.01)
    status, _, _ = r.handle(body, {})
    assert status == 200
    snap = r.metrics.snapshot()
    assert snap["readmissions"] == 1
    assert r._endpoints[EP0].admitted


def test_retry_exhaustion_returns_502():
    r = Router([EP0, EP1], config=_fast_config(retry_max=3))
    _stub(r, {EP0: ConnectionError("x"), EP1: ConnectionError("x")})
    status, _, body = r.handle(_body([1, 2, 3], timeout_s=5.0), {})
    assert status == 502
    assert b"forward attempt(s) failed" in body
    assert r.metrics.snapshot()["requests"]["error"] == 1


def test_budget_exhaustion_returns_504_with_deadline_header():
    r = Router([EP0, EP1],
               config=_fast_config(retry_max=1000, retry_base_s=0.02,
                                   retry_cap_s=0.02,
                                   eject_failures=1000))
    _stub(r, {EP0: ConnectionError("x"), EP1: ConnectionError("x")})
    t0 = time.monotonic()
    status, headers, _ = r.handle(
        _body([1, 2, 3]), {"X-Request-Timeout-S": "0.15"})
    assert status == 504
    assert time.monotonic() - t0 < 2.0  # bounded by the budget, not retries
    assert dict(headers).get("X-Deadline-Remaining-S") is not None
    assert r.metrics.snapshot()["requests"]["expired"] == 1


def test_503_passthrough_clamps_retry_after_to_budget():
    r = Router([EP0, EP1], config=_fast_config(retry_max=2))
    shed = (503, {"Retry-After": "60"}, b'{"error": "shed"}')
    _stub(r, {EP0: shed, EP1: shed})
    status, headers, _ = r.handle(
        _body([1, 2, 3]), {"X-Request-Timeout-S": "1.0"})
    assert status == 503
    ra = dict(headers).get("Retry-After")
    # The backend advertised 60s; the client only has ~1s — a compliant
    # client must never be told to sleep its whole budget away.
    assert ra is not None and float(ra) <= 1.0
    # Backpressure is not failure: nobody got ejected.
    assert r.metrics.snapshot()["ejections"] == 0


def test_hedging_beats_slow_primary():
    r = Router([EP0, EP1], config=_fast_config(hedge_s=0.02))
    slow_body = json.dumps({"tokens": [9, 9, 9]}).encode()

    def slow():
        time.sleep(0.3)
        return 200, {}, slow_body

    _stub(r, {EP0: slow, EP1: (200, {}, _OK_BODY)})
    body = _body(_key_for(r, EP0, want_second=EP1))
    t0 = time.monotonic()
    status, _, out = r.handle(body, {})
    dt = time.monotonic() - t0
    assert status == 200 and out == _OK_BODY  # the hedge's answer
    assert dt < 0.3  # did not wait for the slow primary
    snap = r.metrics.snapshot()
    assert snap["hedges"] == 1 and snap["hedges_won"] == 1


def test_no_candidate_waits_for_probe_window_instead_of_shedding():
    """Zero-lost discipline: a fully-ejected fleet is transient — when
    the client budget covers the next half-open window, the router waits
    and retries instead of shedding."""
    r = Router([EP0], config=_fast_config(eject_failures=1, retry_max=50))
    flips = {"n": 0}

    def flaky():
        flips["n"] += 1
        if flips["n"] <= 1:
            return ConnectionError("first attempt dies")
        return 200, {}, _OK_BODY

    _stub(r, {EP0: flaky})
    status, _, out = r.handle(
        _body([1, 2, 3]), {"X-Request-Timeout-S": "5"})
    assert status == 200 and out == _OK_BODY
    snap = r.metrics.snapshot()
    assert snap["ejections"] == 1 and snap["readmissions"] == 1


# ---------------------------------------------------------------------------
# faultline at router.forward
# ---------------------------------------------------------------------------

def test_faultline_drop_and_slow_route():
    r = Router([EP0, EP1], config=_fast_config(eject_failures=5))
    calls = _stub(r, {EP0: (200, {}, _OK_BODY), EP1: (200, {}, _OK_BODY)})
    body = _body(_key_for(r, EP0, want_second=EP1))
    plan = fl.install(fl.parse_plan(
        f"drop-route:{EP0}@0*1/router.forward,"
        f"slow-route:{EP1}@0*1~0.1/router.forward"))
    try:
        t0 = time.monotonic()
        status, _, _ = r.handle(body, {})
        dt = time.monotonic() - t0
    finally:
        fl.uninstall()
    # The drop killed the EP0 attempt before transport; the failover to
    # EP1 ate the slow-route stall; the request still answered.
    assert status == 200
    assert calls == [EP1]
    assert dt >= 0.1
    assert [e["kind"] for e in plan.log] == ["drop-route", "slow-route"]
    assert r.metrics.snapshot()["retries"] == 1


def test_faultline_blackhole_endpoint():
    r = Router([EP0, EP1], config=_fast_config(eject_failures=5))
    calls = _stub(r, {EP0: (200, {}, _OK_BODY), EP1: (200, {}, _OK_BODY)})
    body = _body(_key_for(r, EP0, want_second=EP1))
    fl.install(fl.parse_plan(
        f"blackhole-endpoint:{EP0}@0*1~0.2/router.forward"))
    try:
        status, _, _ = r.handle(body, {})
    finally:
        fl.uninstall()
    # The blackhole gate fires before the transport: EP0 is never
    # actually contacted, and the request fails over.
    assert status == 200 and calls == [EP1]
    assert r._endpoints[EP0].blackholed_until > time.monotonic() - 0.2


def test_faultline_kill_rank_with_scale_down_reconciles():
    """ISSUE 18 chaos satellite: kill-rank at router.forward concurrent
    with a backend scale-down — the drained replica is never routed to
    while ejected, and the router's ejection/readmission counters
    reconcile with the scheduler's ``replica_events``."""
    adapter = _mlp_adapter()
    sched = build_replicas(lambda: adapter, num_replicas=2,
                           metrics=ServeMetrics())
    r = Router([EP0, EP1], config=_fast_config())
    behavior = {EP0: (200, {}, _OK_BODY), EP1: (200, {}, _OK_BODY)}
    calls = _stub(r, behavior)
    body = _body(_key_for(r, EP0, want_second=EP1))
    # The backend control plane scales replica-0 out...
    sched.mark_dead("replica-0", reason="scale-down")
    # ...while the router independently detects the loss at forward time.
    fl.install(fl.parse_plan(f"kill-rank:{EP0}@0*1/router.forward"))
    try:
        status, _, _ = r.handle(body, {})
    finally:
        fl.uninstall()
    assert status == 200  # failover absorbed the kill
    assert not r._endpoints[EP0].admitted
    # While ejected, EP0 receives no traffic at all.
    calls.clear()
    status, _, _ = r.handle(body, {})
    assert status == 200 and EP0 not in calls
    # Recovery on both planes: scheduler readmits the replica, the
    # router's half-open probe readmits the endpoint.
    sched.mark_alive("replica-0", reason="scale-up")
    time.sleep(r.config.probe_s + 0.01)
    status, _, _ = r.handle(body, {})
    assert status == 200
    rsnap = r.metrics.snapshot()
    events = sched.metrics.snapshot()["replica_events"]
    assert rsnap["ejections"] == events["mark_dead"] == 1
    assert rsnap["readmissions"] == events["mark_alive"] == 1


# ---------------------------------------------------------------------------
# drain satellites (real HTTP listeners)
# ---------------------------------------------------------------------------

class _SlowPrefillAdapter(MLPAdapter):
    """Holds each request in flight long enough for the drain tests to
    observe it."""

    def prefill(self, cache, prompts, slots):
        time.sleep(0.4)
        return super().prefill(cache, prompts, slots)


def _post(port, payload, headers=(), timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"}, **dict(headers)))
    return urllib.request.urlopen(req, timeout=timeout)


def test_serve_server_drains_gracefully():
    """ISSUE 18 satellite: SIGTERM-path drain — in-flight requests
    finish, new ones are refused with 503 + ``Connection: close`` and a
    header-budget-clamped Retry-After, and ``drain()`` reports a clean
    exit."""
    mlp = create_mlp(features=(16, VOCAB))
    params = mlp.init(jax.random.PRNGKey(3),
                      np.zeros((1, VOCAB), np.float32))["params"]
    adapter = _SlowPrefillAdapter(mlp, params, vocab_size=VOCAB,
                                  max_len=128)
    sched = build_replicas(lambda: adapter, num_replicas=1,
                           metrics=ServeMetrics())
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    results = {}

    def inflight():
        with _post(port, {"tokens": [3, 1], "max_new_tokens": 2}) as resp:
            results["status"] = resp.status
            results["body"] = json.loads(resp.read())

    t = threading.Thread(target=inflight, daemon=True)
    t.start()
    time.sleep(0.15)  # request is inside the slow prefill
    server.httpd.begin_drain()
    # New work is refused — with the drain contract's exact headers,
    # Retry-After clamped by the header budget even though no Request
    # object was ever constructed (the clamp satellite).
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(port, {"tokens": [3, 1], "max_new_tokens": 2},
              headers={"X-Request-Timeout-S": "2"})
    assert e.value.code == 503
    assert e.value.headers.get("Connection") == "close"
    assert float(e.value.headers.get("Retry-After")) <= 2.0
    assert e.value.headers.get("X-Deadline-Remaining-S") is not None
    # The in-flight request still completes, then drain reports clean.
    assert server.drain(grace_s=10) is True
    t.join(timeout=10)
    assert results["status"] == 200
    assert results["body"]["tokens"]


def test_router_server_drain_refusal_clamps_retry_after():
    """Same drain contract one tier up: a draining hvdroute refuses with
    503 + ``Connection: close``, Retry-After clamped by the header
    budget, and counts the refusal."""
    r = Router([EP0], config=_fast_config(probe_s=30.0))
    server = RouterServer(r)
    port = server.start(port=0, host="127.0.0.1")
    try:
        server.httpd.begin_drain()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"tokens": [1, 2, 3]},
                  headers={"X-Request-Timeout-S": "2"})
        assert e.value.code == 503
        assert e.value.headers.get("Connection") == "close"
        # probe_s would hint 30s; the client only has 2.
        assert float(e.value.headers.get("Retry-After")) <= 2.0
        assert r.metrics.snapshot()["requests"]["refused"] == 1
        # /healthz keeps answering during drain and reports it.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            health = json.loads(resp.read())
        assert health["draining"] is True
    finally:
        server.stop()


def test_router_server_routes_and_exports_metrics():
    """End-to-end over real sockets: RouterServer → Router → a stubbed
    transport standing in for the backend fleet."""
    r = Router([EP0, EP1], config=_fast_config())
    _stub(r, {EP0: (200, {}, _OK_BODY), EP1: (200, {}, _OK_BODY)})
    server = RouterServer(r)
    port = server.start(port=0, host="127.0.0.1")
    try:
        with _post(port, {"tokens": [1, 2, 3],
                          "max_new_tokens": 2}) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["tokens"] == [1, 2, 3]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert 'hvd_route_requests_total{outcome="ok"} 1' in text
        assert "hvd_route_endpoint_admitted" in text
    finally:
        server.stop()
