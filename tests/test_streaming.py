"""ISSUE 19: hvdstream — token streaming over SSE/chunked transfer.

Pins the tentpole layer by layer:

* wire helpers — SSE encode/parse roundtrip, chunk framing, the
  ``stream`` opt-in (body flag / Accept header), error→status mapping;
* TokenStream — position-keyed dedupe (failover replay invisible),
  bounded-queue coalescing that never drops, first-terminal-wins;
* HTTP server — streamed == buffered bit-exactness across pow2 prompt
  buckets (greedy AND sampled), mid-stream deadline expiry as a
  terminal ``error`` event, client disconnect aborting the sequence in
  the engine (``client_gone`` counted, slot freed);
* faultline — the new ``stream-disconnect`` / ``slow-client`` kinds at
  the ``stream.emit`` point;
* root span — every POST outcome (buffered, streamed, 404, drain
  refusal) emits exactly one ``http-handle`` root span with its final
  status (the ISSUE 19 bugfix satellite);
* router — SSE pass-through without buffering, pre-first-byte failover
  preserved, post-first-byte failure surfacing as a terminal error
  event (never a silent retry), hedging claimed at first byte with the
  loser closing its own connection;
* controller — the env-gated TTFT windowed-p99 pressure term;
* soak (``slow``) — a 4-replica streamed storm with a replica killed
  mid-stream: every client sees its exact sequence once (zero lost,
  zero duplicated tokens).
"""

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import faultline as fl
from horovod_tpu.obs import merge as mg
from horovod_tpu.obs import tracing as tr
from horovod_tpu.serve import (ControllerConfig, ControllerState,
                               DeadlineExceededError, FleetSnapshot,
                               InferenceEngine, MLPAdapter, QueueFullError,
                               Replica, ReplicaScheduler, Request, Router,
                               RouterConfig, ServeMetrics, ServeServer)
from horovod_tpu.serve.controller import decide
from horovod_tpu.serve.streaming import (CHUNK_TERMINATOR, TokenStream,
                                         chunk_frame, encode_sse,
                                         error_status_for, parse_sse,
                                         wants_stream)
from horovod_tpu.models import create_mlp

VOCAB = 31


@pytest.fixture(autouse=True)
def _clean_world():
    """No leaked faultline plan or tracer across tests (the faultline /
    obs suites' discipline)."""
    fl.uninstall()
    tr.uninstall()
    yield
    fl.uninstall()
    tr.uninstall()


# -- shared harness ----------------------------------------------------------

def _mlp_adapter(seed=3, vocab=VOCAB, max_len=512):
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return MLPAdapter(mlp, params, vocab_size=vocab, max_len=max_len)


def _mlp_chain(adapter, prompt, n):
    """Ground truth for the MLP Markov chain (greedy)."""
    seq, tok = [], prompt[-1]
    for _ in range(n):
        tok = int(adapter._apply(np.asarray([tok], np.int32))[0])
        seq.append(tok)
    return seq


class _SlowMLP(MLPAdapter):
    """Visible per-decode-step cost so a stream stays open long enough
    to fault (deadline expiry, disconnect, kill) deterministically."""

    delay_s = 0.02

    def decode_paged(self, cache, tokens, positions, tables):
        time.sleep(self.delay_s)
        return MLPAdapter.decode(self, cache, tokens, positions)


def _slow_adapter(seed=3, vocab=VOCAB):
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return _SlowMLP(mlp, params, vocab_size=vocab, max_len=512)


def _fleet_server(adapter_fn=_mlp_adapter, n=1, request_timeout_s=60,
                  **engine_kw):
    engine_kw.setdefault("max_batch", 4)
    replicas = [Replica(f"replica-{i}", None,
                        InferenceEngine(adapter_fn(), kv_mode="paged",
                                        metrics=ServeMetrics(),
                                        replica_id=f"replica-{i}",
                                        **engine_kw))
                for i in range(n)]
    sched = ReplicaScheduler(replicas, metrics=replicas[0].engine.metrics)
    server = ServeServer(sched, request_timeout_s=request_timeout_s)
    port = server.start(port=0, host="127.0.0.1")
    return server, sched, port


def _post(port, payload, headers=(), path="/generate", timeout=30):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        headers=dict({"Content-Type": "application/json"},
                     **dict(headers)))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _stream_post(port, payload, headers=(), hangup_after=None, timeout=30):
    """POST /generate and consume the SSE stream incrementally.

    Returns ``(status, resp_headers, buffered_body_or_None, events)``;
    a non-stream answer (pre-first-byte shed/400) comes back buffered
    with ``events is None``.  ``hangup_after=k`` slams the connection
    shut after the k-th token event (the client-disconnect probe)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/generate", body=json.dumps(payload).encode(),
                     headers=dict({"Content-Type": "application/json"},
                                  **dict(headers)))
        resp = conn.getresponse()
        ctype = resp.getheader("Content-Type") or ""
        if resp.status != 200 or "text/event-stream" not in ctype:
            data = resp.read()
            return (resp.status, dict(resp.getheaders()),
                    json.loads(data or b"{}"), None)
        raw = b""
        events = []
        while True:
            try:
                chunk = resp.read1(8192)
            except (http.client.HTTPException, OSError):
                break  # server hung up mid-stream (faultline disconnect)
            if not chunk:
                break
            raw += chunk
            # Parse only COMPLETE events: read1 can fragment below the
            # SSE block boundary.
            cut = raw.rfind(b"\n\n")
            events = parse_sse(raw[:cut + 2]) if cut >= 0 else []
            ntok = sum(1 for e in events if e[0] == "token")
            if hangup_after is not None and ntok >= hangup_after:
                resp.close()  # drops the last socket ref: FIN to server
                return resp.status, dict(resp.getheaders()), None, events
            if events and events[-1][0] in ("done", "error"):
                break
        return resp.status, dict(resp.getheaders()), None, events
    finally:
        conn.close()


def _stream_tokens(events):
    return [t for e in events if e[0] == "token" for t in e[1]["tokens"]]


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------

def test_sse_roundtrip_and_chunk_framing():
    evs = [("token", {"index": 0, "tokens": [5, 7]}),
           ("done", {"request_id": "r-1", "usage": {"total_tokens": 9}})]
    raw = b"".join(encode_sse(k, d) for k, d in evs)
    assert parse_sse(raw) == evs
    framed = chunk_frame(b"hello")
    assert framed == b"5\r\nhello\r\n"
    assert CHUNK_TERMINATOR == b"0\r\n\r\n"
    # Frame length is hex.
    assert chunk_frame(b"x" * 26).startswith(b"1a\r\n")


def test_wants_stream_body_flag_and_accept_header():
    assert wants_stream({"stream": True}, {})
    assert not wants_stream({"stream": False}, {})
    assert not wants_stream({}, {})
    assert wants_stream({}, {"Accept": "text/event-stream"})
    assert not wants_stream({}, {"Accept": "application/json"})


def test_error_status_mapping_mirrors_buffered_path():
    from horovod_tpu.serve import NoHealthyReplicaError
    assert error_status_for(QueueFullError("full")) == 503
    assert error_status_for(NoHealthyReplicaError("none")) == 503
    assert error_status_for(DeadlineExceededError("late")) == 504
    assert error_status_for(TimeoutError("cap")) == 504
    assert error_status_for(ValueError("bad")) == 400
    assert error_status_for(RuntimeError("boom")) == 500


# ---------------------------------------------------------------------------
# TokenStream
# ---------------------------------------------------------------------------

def test_token_stream_delivers_in_order_and_finish_flushes_tail():
    s = TokenStream(maxlen=64)
    s.publish(0, 11)
    s.publish(1, 12)
    # finish() flushes the unpublished tail (positions 2, 3) before the
    # terminal — concatenated == buffered is structural, not a race.
    s.finish([11, 12, 13, 14])
    got, events = [], []
    while True:
        ev = s.next_event(timeout=1.0)
        events.append(ev)
        if ev[0] != "token":
            break
        got.extend(ev[1]["tokens"])
    assert got == [11, 12, 13, 14]
    assert events[-1] == ("done", None)
    assert s.counters() == {"published": 4, "coalesced": 0,
                            "duplicates": 0}
    # The terminal is sticky: consumers that poll again still see it.
    assert s.next_event(timeout=0.1) == ("done", None)


def test_token_stream_dedupes_failover_replay():
    s = TokenStream(maxlen=64)
    for pos, tok in enumerate([4, 5, 6]):
        s.publish(pos, tok)
    # Failover replay: the survivor re-decodes from position 0 and
    # re-publishes the same (seeded-identical) tokens.
    for pos, tok in enumerate([4, 5, 6, 7]):
        s.publish(pos, tok)
    s.finish([4, 5, 6, 7])
    got = []
    while True:
        ev = s.next_event(timeout=1.0)
        if ev[0] != "token":
            break
        got.extend(ev[1]["tokens"])
    assert got == [4, 5, 6, 7]  # exactly once, no gap, no duplicate
    assert s.counters()["duplicates"] == 3
    assert s.counters()["published"] == 4


def test_token_stream_bounded_queue_coalesces_never_drops():
    s = TokenStream(maxlen=2)
    toks = list(range(10, 20))
    for pos, tok in enumerate(toks):
        s.publish(pos, tok)
    # Nothing consumed: the queue held at most maxlen events by
    # coalescing into the newest — and no token was lost.
    assert s.counters()["coalesced"] == len(toks) - 2
    s.finish(toks)
    got, n_events = [], 0
    while True:
        ev = s.next_event(timeout=1.0)
        if ev[0] != "token":
            break
        n_events += 1
        got.extend(ev[1]["tokens"])
    assert got == toks
    assert n_events == 2


def test_token_stream_first_terminal_wins_and_abort_is_idempotent():
    s = TokenStream(maxlen=4)
    s.publish(0, 1)
    exc = DeadlineExceededError("expired mid-stream")
    s.abort(exc)
    s.abort(RuntimeError("second terminal must lose"))
    s.finish([1, 2, 3])  # post-abort finish must not override
    assert s.next_event(timeout=1.0) == ("token", {"index": 0,
                                                  "tokens": [1]})
    kind, err = s.next_event(timeout=1.0)
    assert kind == "error" and err is exc
    # Post-terminal publishes are dropped outright.
    s.publish(5, 9)
    assert s.next_event(timeout=0.1) == ("error", exc)


def test_token_stream_next_event_times_out_empty():
    s = TokenStream(maxlen=4)
    t0 = time.monotonic()
    assert s.next_event(timeout=0.05) is None
    assert time.monotonic() - t0 < 1.0


def test_token_stream_logprobs_ride_token_events():
    s = TokenStream(maxlen=64, logprobs=True)
    s.publish(0, 3, {"token": 3, "logprob": -0.5})
    s.finish([3], [{"token": 3, "logprob": -0.5}])
    kind, data = s.next_event(timeout=1.0)
    assert kind == "token"
    assert data["logprobs"] == [{"token": 3, "logprob": -0.5}]


# ---------------------------------------------------------------------------
# HTTP: streamed == buffered
# ---------------------------------------------------------------------------

def test_stream_matches_buffered_across_pow2_buckets():
    server, sched, port = _fleet_server()
    ad = sched.replicas[0].engine.adapter
    try:
        # Prompt lengths straddling pow2 bucket edges, greedy.
        for plen in (3, 8, 9, 17):
            prompt = [(5 * plen + j) % VOCAB for j in range(plen)]
            payload = {"tokens": prompt, "max_new_tokens": 12}
            _, buffered = _post(port, payload)
            status, _, _, events = _stream_post(
                port, dict(payload, stream=True))
            assert status == 200
            assert events[-1][0] == "done"
            assert _stream_tokens(events) == buffered["tokens"]
            assert buffered["tokens"] == _mlp_chain(ad, prompt, 12)
            # The done event carries the buffered body's outcome fields
            # verbatim (one builder) plus the stream counters.
            done = events[-1][1]
            for key in ("request_id", "finish_reason", "usage", "seed",
                        "qos", "tenant"):
                assert key in done, key
            assert done["usage"] == buffered["usage"]
            assert done["stream"]["published"] == len(buffered["tokens"])
            assert done["stream"]["duplicates"] == 0
        # Sampled: same seed -> streamed tokens == buffered tokens.
        sampled = {"tokens": [1, 2, 3], "max_new_tokens": 10,
                   "temperature": 0.8, "seed": 123}
        _, buf = _post(port, sampled)
        _, _, _, events = _stream_post(port, dict(sampled, stream=True))
        assert _stream_tokens(events) == buf["tokens"]
        assert events[-1][1]["seed"] == buf["seed"] == 123
    finally:
        server.stop()


def test_accept_header_opts_into_streaming_without_body_flag():
    server, _, port = _fleet_server()
    try:
        status, hdrs, _, events = _stream_post(
            port, {"tokens": [1, 2, 3], "max_new_tokens": 4},
            headers=[("Accept", "text/event-stream")])
        assert status == 200
        assert "text/event-stream" in hdrs.get("Content-Type", "")
        assert events[-1][0] == "done"
        assert len(_stream_tokens(events)) == 4
    finally:
        server.stop()


def test_stream_pre_first_byte_error_answers_buffered_400():
    server, _, port = _fleet_server()
    try:
        # schema without eos_id: rejected before admission — the client
        # sees an ordinary buffered 400, not a broken stream.
        status, _, body, events = _stream_post(
            port, {"tokens": [1], "stream": True,
                   "schema": {"type": "boolean"}})
        assert status == 400 and events is None
        assert "eos_id" in body["error"]
    finally:
        server.stop()


def test_mid_stream_deadline_ends_with_terminal_504_error_event():
    server, sched, port = _fleet_server(_slow_adapter)
    eng = sched.replicas[0].engine
    try:
        status, _, _, events = _stream_post(
            port, {"tokens": [1, 2], "max_new_tokens": 400,
                   "timeout_s": 0.5, "stream": True}, timeout=30)
        assert status == 200  # headers were sent before the expiry
        kinds = [e[0] for e in events]
        assert kinds.count("token") >= 1, events
        assert kinds[-1] == "error"
        err = events[-1][1]
        assert err["code"] == 504
        assert "expired" in err["error"] or "deadline" in err["error"]
        # The engine reaped the sequence (slot freed).
        deadline = time.monotonic() + 10
        while eng.active_count and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.active_count == 0
    finally:
        server.stop()


def test_client_disconnect_aborts_sequence_and_counts_client_gone():
    server, sched, port = _fleet_server(_slow_adapter)
    eng = sched.replicas[0].engine
    try:
        status, _, _, events = _stream_post(
            port, {"tokens": [3, 4], "max_new_tokens": 400,
                   "stream": True}, hangup_after=1)
        assert status == 200
        assert len(_stream_tokens(events)) >= 1
        # The engine observes the hangup at its next write and reaps
        # the still-decoding sequence; the outcome is client_gone.
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if (eng.active_count == 0 and eng.metrics.snapshot()
                    ["requests"].get("client_gone", 0) >= 1):
                break
            time.sleep(0.02)
        assert eng.active_count == 0
        assert eng.metrics.snapshot()["requests"]["client_gone"] >= 1
        assert eng.kv_stats()["used"] == 0
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# faultline: stream-disconnect / slow-client at stream.emit
# ---------------------------------------------------------------------------

def test_faultline_stream_kinds_parse_with_default_point():
    plan = fl.parse_plan("stream-disconnect@3,slow-client*5~0.04", seed=9)
    disc, slow = plan.specs
    assert disc.kind == "stream-disconnect"
    assert disc.point == "stream.emit" and disc.step == 3
    assert slow.kind == "slow-client" and slow.point == "stream.emit"
    assert slow.repeat == 5 and slow.param == pytest.approx(0.04)
    # Round-trips through the spec grammar used by HVD_FAULTLINE_PLAN.
    assert fl.parse_plan("stream-disconnect/stream.emit",
                         seed=1).specs[0].point == "stream.emit"


def test_faultline_stream_disconnect_reaps_sequence():
    server, sched, port = _fleet_server(_slow_adapter)
    eng = sched.replicas[0].engine
    fl.install(fl.FaultPlan(
        [fl.FaultSpec("stream-disconnect", step=2)], seed=1))
    try:
        status, _, _, events = _stream_post(
            port, {"tokens": [5, 6], "max_new_tokens": 400,
                   "stream": True}, timeout=30)
        # The injected BrokenPipeError truncates the stream: no
        # terminal event reached the client.
        assert status == 200
        assert [e[0] for e in events].count("token") >= 1
        assert not events or events[-1][0] == "token"
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if (eng.active_count == 0 and eng.metrics.snapshot()
                    ["requests"].get("client_gone", 0) >= 1):
                break
            time.sleep(0.02)
        assert eng.active_count == 0
        assert eng.metrics.snapshot()["requests"]["client_gone"] >= 1
    finally:
        server.stop()


def test_faultline_slow_client_coalesces_bounded_queue(monkeypatch):
    monkeypatch.setenv("HVD_SERVE_STREAM_QUEUE", "2")
    server, _, port = _fleet_server()
    try:
        payload = {"tokens": [7, 8], "max_new_tokens": 30}
        _, buffered = _post(port, payload)
        fl.install(fl.FaultPlan(
            [fl.FaultSpec("slow-client", step=0, repeat=1000,
                          param=0.03)], seed=1))
        _, _, _, events = _stream_post(port, dict(payload, stream=True),
                                       timeout=60)
        assert events[-1][0] == "done"
        # Stalled handler + bounded queue: tokens coalesced into fewer,
        # fatter events — and the concatenation still matches buffered
        # bit-for-bit (never dropped).
        assert _stream_tokens(events) == buffered["tokens"]
        n_token_events = sum(1 for e in events if e[0] == "token")
        assert n_token_events < 30
        assert events[-1][1]["stream"]["coalesced"] > 0
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# root span: every POST outcome emits exactly one http-handle root
# ---------------------------------------------------------------------------

def test_every_post_outcome_emits_one_http_handle_root_span(tmp_path):
    shard_dir = tmp_path / "shards"
    tr.install(tr.Tracer(sample=1.0, shard_dir=str(shard_dir)))
    server, _, port = _fleet_server()
    tids = {"buffered": "aaaaaaaaaaaaaa01", "streamed": "aaaaaaaaaaaaaa02",
            "notfound": "aaaaaaaaaaaaaa03", "drained": "aaaaaaaaaaaaaa04"}
    try:
        status, _ = _post(port, {"tokens": [1, 2], "max_new_tokens": 3},
                          headers=[("X-Trace-Id", tids["buffered"])])
        assert status == 200
        status, _, _, events = _stream_post(
            port, {"tokens": [1, 2], "max_new_tokens": 3, "stream": True},
            headers=[("X-Trace-Id", tids["streamed"])])
        assert status == 200 and events[-1][0] == "done"
        status, _ = _post(port, {"tokens": [1]}, path="/nope",
                          headers=[("X-Trace-Id", tids["notfound"])])
        assert status == 404
        # Drain refusal: the regression this pins — the refusal used to
        # answer before the span machinery and left traced sheds
        # rootless.
        server.httpd.begin_drain()
        status, _ = _post(port, {"tokens": [1]},
                          headers=[("X-Trace-Id", tids["drained"])])
        assert status == 503
    finally:
        server.stop()
    tr.uninstall()
    traces = mg.spans_by_trace(mg.load_shards(str(shard_dir)))
    expect = {"buffered": 200, "streamed": 200,
              "notfound": 404, "drained": 503}
    for label, want_status in expect.items():
        spans = [s for s in traces.get(tids[label], [])
                 if s["type"] == "span" and s["name"] == "http-handle"]
        assert len(spans) == 1, (label, spans)
        root = spans[0]
        assert root["args"]["status"] == want_status, label
    # The streamed request's root covers the route hop beneath it.
    streamed = [s for s in traces[tids["streamed"]]
                if s["type"] == "span"]
    assert any(s["name"] == "route" for s in streamed)


# ---------------------------------------------------------------------------
# router: SSE pass-through, first-byte hedging, terminal error events
# ---------------------------------------------------------------------------

EP0, EP1 = "10.0.0.1:8000", "10.0.0.2:8000"


class _FakeReader:
    """Stands in for router._StreamReader: canned chunks, optional
    mid-stream failure, close tracking with the on_close contract."""

    def __init__(self, chunks, fail_after=None):
        self.chunks = list(chunks)
        self.fail_after = fail_after
        self.reads = 0
        self.closed = False
        self.on_close = None

    def read1(self, n=8192):
        if self.fail_after is not None and self.reads >= self.fail_after:
            raise OSError("backend died mid-stream")
        self.reads += 1
        return self.chunks.pop(0) if self.chunks else b""

    def close(self):
        if self.closed:
            return
        self.closed = True
        if self.on_close is not None:
            self.on_close()


class _FakeClient:
    """Downstream side of Router.handle(stream=...): records frames;
    ``gone_after`` flips write() to False (client hangup)."""

    def __init__(self, gone_after=None):
        self.status = None
        self.headers = None
        self.frames = []
        self.terminated = 0
        self.gone_after = gone_after

    def begin(self, status, headers):
        self.status, self.headers = status, list(headers)

        def write(data):
            if data is None:
                self.terminated += 1
                return True
            if self.gone_after is not None \
                    and len(self.frames) >= self.gone_after:
                return False
            self.frames.append(data)
            return True
        return write


def _fast_config(**overrides):
    base = dict(retry_base_s=0.001, retry_cap_s=0.005, probe_s=0.05,
                eject_failures=2, block_tokens=4)
    base.update(overrides)
    return RouterConfig(**base)


def _stub_stream(router, behavior, calls=None):
    """Replace the STREAMING transport seam; behavior[name] is a
    4-tuple, an Exception, or a callable returning either."""
    calls = [] if calls is None else calls

    def transport(host, port, method, path, body, headers, timeout_s):
        name = f"{host}:{port}"
        calls.append(name)
        out = behavior[name]
        if callable(out):
            out = out()
        if isinstance(out, Exception):
            raise out
        return out

    router._transport_stream = transport
    return calls


def _key_for(router, target, want_second=None):
    for s in range(4096):
        p = [(7 * s + j) % VOCAB for j in range(12)]
        order = router._ring.lookup(router.affinity_key(p))
        if order[0] == target and \
                (want_second is None or order[1] == want_second):
            return p
    raise AssertionError(f"no prompt routes to {target}")


def _sse_chunks(tokens):
    frames = [encode_sse("token", {"index": i, "tokens": [t]})
              for i, t in enumerate(tokens)]
    frames.append(encode_sse("done", {"request_id": "r-1",
                                      "finish_reason": "length"}))
    return frames


def _stream_body(tokens):
    return json.dumps({"tokens": tokens, "stream": True,
                       "max_new_tokens": 4}).encode()


def test_router_stream_passthrough_pipes_without_buffering():
    router = Router([EP0, EP1], config=_fast_config())
    prompt = _key_for(router, EP0)
    chunks = _sse_chunks([9, 8, 7])
    reader = _FakeReader(chunks)
    calls = _stub_stream(router, {
        EP0: (200, {"Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                    "X-Backend-Secret": "must-not-forward"},
              None, reader)})
    client = _FakeClient()
    out = router.handle(_stream_body(prompt), {}, stream=client.begin)
    assert out == (200, None, None)  # body already piped
    assert calls == [EP0]
    assert client.status == 200
    assert b"".join(client.frames) == b"".join(chunks)
    assert client.terminated == 1  # exactly one end-of-body
    # Hop-by-hop / backend-internal headers are not forwarded.
    names = [k.lower() for k, _ in client.headers]
    assert "x-backend-secret" not in names
    assert "content-type" in names
    # The reader was closed and the inflight gauge released.
    assert reader.closed
    assert router._endpoints[EP0].inflight == 0
    assert router.metrics.snapshot()["requests"]["ok"] == 1


def test_router_stream_post_first_byte_failure_is_terminal_not_retried():
    router = Router([EP0, EP1], config=_fast_config())
    prompt = _key_for(router, EP0, want_second=EP1)
    first = encode_sse("token", {"index": 0, "tokens": [9]})
    reader = _FakeReader([first], fail_after=1)
    calls = _stub_stream(router, {
        EP0: (200, {"Content-Type": "text/event-stream"}, None, reader),
        EP1: (200, {"Content-Type": "text/event-stream"}, None,
              _FakeReader(_sse_chunks([1])))})
    client = _FakeClient()
    status, hdrs, body = router.handle(_stream_body(prompt), {},
                                       stream=client.begin)
    # The client already consumed EP0's first token — a silent retry on
    # EP1 would re-send it.  The failure surfaces as a terminal SSE
    # error event instead.
    assert calls == [EP0]
    assert status == 200 and hdrs is None and body is None
    events = parse_sse(b"".join(client.frames))
    assert events[0] == ("token", {"index": 0, "tokens": [9]})
    assert events[-1][0] == "error"
    assert events[-1][1]["code"] == 502
    assert EP0 in events[-1][1]["error"]
    assert reader.closed
    assert router.metrics.snapshot()["requests"]["error"] == 1


def test_router_stream_client_gone_closes_backend_connection():
    router = Router([EP0, EP1], config=_fast_config())
    prompt = _key_for(router, EP0)
    reader = _FakeReader(_sse_chunks([1, 2, 3]))
    _stub_stream(router, {
        EP0: (200, {"Content-Type": "text/event-stream"}, None, reader)})
    client = _FakeClient(gone_after=1)
    status, hdrs, body = router.handle(_stream_body(prompt), {},
                                       stream=client.begin)
    assert (status, hdrs, body) == (200, None, None)
    # Backend connection closed -> the engine there sees the hangup and
    # aborts the sequence; no terminator was written downstream.
    assert reader.closed
    assert client.terminated == 0
    assert router._endpoints[EP0].inflight == 0
    assert router.metrics.snapshot()["requests"]["client_gone"] == 1


def test_router_stream_pre_first_byte_failure_still_fails_over():
    router = Router([EP0, EP1], config=_fast_config())
    prompt = _key_for(router, EP0, want_second=EP1)
    chunks = _sse_chunks([4, 5])
    calls = _stub_stream(router, {
        EP0: ConnectionError("connect refused"),
        EP1: (200, {"Content-Type": "text/event-stream"}, None,
              _FakeReader(chunks))})
    client = _FakeClient()
    status, hdrs, body = router.handle(_stream_body(prompt), {},
                                       stream=client.begin)
    # Before the first byte the buffered retry/failover machinery is
    # intact: the stream is served whole from the next candidate.
    assert calls == [EP0, EP1]
    assert (status, hdrs, body) == (200, None, None)
    assert b"".join(client.frames) == b"".join(chunks)
    assert router.metrics.snapshot()["requests"]["ok"] == 1


def test_router_stream_non_sse_answer_passes_through_buffered():
    router = Router([EP0, EP1], config=_fast_config())
    prompt = _key_for(router, EP0)
    err = json.dumps({"error": "schema requires eos_id"}).encode()
    calls = _stub_stream(router, {
        EP0: (400, {"Content-Type": "application/json"}, err, None)})
    client = _FakeClient()
    status, hdrs, body = router.handle(_stream_body(prompt), {},
                                       stream=client.begin)
    # Backend declined to stream (400 before the first token): the
    # router answers buffered, exactly like the non-streamed path.
    assert calls == [EP0]  # definitive — no retry
    assert status == 400 and body == err
    assert client.status is None  # stream callback never engaged


def test_router_stream_hedge_winner_claimed_at_first_byte():
    router = Router([EP0, EP1], config=_fast_config(hedge_s=0.02))
    prompt = _key_for(router, EP0, want_second=EP1)
    loser = _FakeReader(_sse_chunks([1, 1, 1]))
    winner_chunks = _sse_chunks([2, 2])
    winner = _FakeReader(winner_chunks)

    def slow_primary():
        time.sleep(0.25)
        return (200, {"Content-Type": "text/event-stream"}, None, loser)

    calls = _stub_stream(router, {
        EP0: slow_primary,
        EP1: (200, {"Content-Type": "text/event-stream"}, None, winner)})
    client = _FakeClient()
    status, hdrs, body = router.handle(_stream_body(prompt), {},
                                       stream=client.begin)
    assert (status, hdrs, body) == (200, None, None)
    # The hedge fired and the secondary was claimed at headers-received
    # (before any body byte): the client sees ONE backend's stream.
    assert sorted(calls) == [EP0, EP1]
    assert b"".join(client.frames) == b"".join(winner_chunks)
    snap = router.metrics.snapshot()
    assert snap["hedges"] == 1 and snap["hedges_won"] == 1
    # The loser's attempt thread closes its own connection when the
    # slow response finally lands — its backend aborts the duplicate.
    deadline = time.monotonic() + 5
    while not loser.closed and time.monotonic() < deadline:
        time.sleep(0.01)
    assert loser.closed
    assert router._endpoints[EP0].inflight == 0
    assert router._endpoints[EP1].inflight == 0


def test_router_buffered_handle_unchanged_without_stream_callback():
    router = Router([EP0, EP1], config=_fast_config())
    prompt = _key_for(router, EP0)
    ok = json.dumps({"tokens": [1, 2]}).encode()

    def transport(host, port, method, path, body, headers, timeout_s):
        return 200, {"Content-Type": "application/json"}, ok

    router._transport = transport
    # A streamed payload with NO downstream stream callback (legacy
    # caller) takes the buffered path end to end.
    status, hdrs, body = router.handle(_stream_body(prompt), {})
    assert status == 200 and body == ok


# ---------------------------------------------------------------------------
# controller: TTFT windowed-p99 pressure term
# ---------------------------------------------------------------------------

def _ctl_cfg(**kw):
    base = dict(poll_s=0.1, min_replicas=1, max_replicas=8,
                queue_high=8.0, queue_low=1.0, up_polls=1, down_polls=4,
                up_cooldown_s=0.0, down_cooldown_s=0.0,
                brownout_polls=2, brownout_clear_polls=3)
    base.update(kw)
    return ControllerConfig(**base).validate()


def test_ttft_slo_breach_is_a_pressure_source():
    cfg = _ctl_cfg(ttft_slo_ms=250.0)
    state = ControllerState()
    snap = FleetSnapshot(healthy=2, spares=1, queued=0,
                         ttft_p99_ms=400.0)
    assert decide(cfg, state, snap, 0.0) == ["scale_up"]


def test_ttft_term_below_slo_or_unobserved_is_quiet():
    cfg = _ctl_cfg(ttft_slo_ms=250.0)
    for snap in (FleetSnapshot(healthy=2, spares=1, queued=0,
                               ttft_p99_ms=100.0),
                 FleetSnapshot(healthy=2, spares=1, queued=0,
                               ttft_p99_ms=None)):
        assert decide(cfg, ControllerState(), snap, 0.0) == []


def test_ttft_term_disabled_by_default():
    cfg = _ctl_cfg()  # ttft_slo_ms defaults to 0 = off
    snap = FleetSnapshot(healthy=2, spares=1, queued=0,
                         ttft_p99_ms=1e9)
    assert decide(cfg, ControllerState(), snap, 0.0) == []


def test_ttft_slo_from_env(monkeypatch):
    monkeypatch.setenv("HVD_SERVE_CTL_TTFT_SLO_MS", "325")
    assert ControllerConfig.from_env().ttft_slo_ms == 325.0
    monkeypatch.delenv("HVD_SERVE_CTL_TTFT_SLO_MS")
    assert ControllerConfig.from_env().ttft_slo_ms == 0.0


def test_serve_metrics_ttft_window_diffs_cleanly():
    m = ServeMetrics()
    for ms in (10, 20, 500):
        m.observe_ttft(ms)
    bounds, counts, total = m.ttft_window()
    # Cumulative histogram export, the windowed_p99 input shape.
    assert total == 3
    assert counts == sorted(counts) and counts[-1] == 3
    assert len(bounds) == len(counts)
    from horovod_tpu.serve.controller import windowed_p99
    p99 = windowed_p99(bounds, None, counts, 0, total)
    assert p99 is not None and p99 >= 20.0


# ---------------------------------------------------------------------------
# soak: streamed storm with a replica killed mid-stream (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_streamed_storm_kill_replica_zero_lost_or_duplicated_tokens():
    """4 replicas, 12 concurrent streamed sessions, one replica killed
    while its sequences are mid-stream.  Failover re-decodes from
    position 0 on a survivor; the sink's position dedupe makes the
    replay invisible — every client's concatenation equals the greedy
    ground truth exactly once."""
    n_sessions, new_tokens = 12, 60
    server, sched, port = _fleet_server(_slow_adapter, n=4, max_batch=4)
    ref = _mlp_adapter()  # same seed: the shared ground-truth chain
    prompts = [[(13 * s + j) % VOCAB for j in range(6 + s % 5)]
               for s in range(n_sessions)]
    results = [None] * n_sessions
    errors = []

    def run(i):
        try:
            results[i] = _stream_post(
                port, {"tokens": prompts[i], "max_new_tokens": new_tokens,
                       "stream": True}, timeout=120)
        except Exception as e:  # pragma: no cover - diagnostic
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(n_sessions)]
    try:
        for t in threads:
            t.start()
        # Kill a replica once it is actually decoding streams.
        victim = None
        deadline = time.monotonic() + 60
        while victim is None and time.monotonic() < deadline:
            for r in sched.replicas:
                if r.engine.active_count > 0:
                    victim = r
                    break
            time.sleep(0.005)
        assert victim is not None, "no replica ever got load"
        time.sleep(0.1)  # let some tokens flow first
        sched.mark_dead(victim.replica_id, "storm kill")
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        requeued = 0
        for i, res in enumerate(results):
            status, _, _, events = res
            assert status == 200, (i, res)
            assert events[-1][0] == "done", (i, events[-1])
            want = _mlp_chain(ref, prompts[i], new_tokens)
            assert _stream_tokens(events) == want, i  # exactly once
            done = events[-1][1]
            if done["requeues"] > 0:
                requeued += 1
                # The replayed prefix was deduped, not re-delivered.
                assert done["stream"]["published"] == new_tokens
        assert requeued > 0, "kill landed after every stream finished"
    finally:
        server.stop()
