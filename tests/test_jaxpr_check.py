"""Jaxpr collective-consistency checker + HVD_ANALYZE trace-time hook.

Acceptance coverage (ISSUE 2): a deliberately branch-mismatched
``lax.cond`` collective and an undeclared axis name are detected; a clean
``DistributedOptimizer`` step passes with zero findings on this jax (the
compat.py-shimmed 0.4.x); the per-step collective census (count + bytes)
for a DistributedOptimizer step is asserted and surfaced via
timeline.py's counter events.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import core as _core
from horovod_tpu.analysis import check_closed_jaxpr, check_step_fn, hook
from horovod_tpu.timeline import Timeline

N = 8


# ---------------------------------------------------------------------------
# Detection: the two seeded inconsistencies
# ---------------------------------------------------------------------------

def test_detects_branch_mismatched_cond_collective():
    def step(x):
        def sync(z):
            return jax.lax.psum(z, "hvd")

        def skip(z):
            return z

        return jax.lax.cond(jnp.sum(x) > 0, sync, skip, x)

    report = check_step_fn(step, (jnp.ones(4),), axis_env=[("hvd", N)])
    assert [f.rule for f in report.findings] == ["HVD102"]
    assert "psum" in report.findings[0].message
    # The census still counts the branch's psum (static upper bound).
    assert report.census["psum"]["count"] == 1


def test_matched_cond_branches_are_clean():
    def step(x):
        def a(z):
            return jax.lax.psum(z, "hvd") * 2.0

        def b(z):
            return jax.lax.psum(z, "hvd") + 1.0

        return jax.lax.cond(jnp.sum(x) > 0, a, b, x)

    report = check_step_fn(step, (jnp.ones(4),), axis_env=[("hvd", N)])
    assert report.ok(), [f.message for f in report.findings]


def test_detects_undeclared_axis_against_declared_set():
    def step(x):
        return jax.lax.psum(x, "tp")

    report = check_step_fn(step, (jnp.ones(4),),
                           axis_env=[("hvd", N), ("tp", 2)],
                           declared_axes=("hvd",))
    assert [f.rule for f in report.findings] == ["HVD101"]
    assert "'tp'" in report.findings[0].message


def test_unbound_axis_trace_failure_reported_not_raised():
    def step(x):
        return jax.lax.psum(x, "no_such_axis")

    report = check_step_fn(step, (jnp.ones(4),), axis_env=[("hvd", N)])
    assert [f.rule for f in report.findings] == ["HVD101"]
    assert "unbound axis" in report.findings[0].message


def test_trace_failure_reported_as_hvd100_not_raised():
    def step(x):
        raise RuntimeError("synthetic trace bomb")

    report = check_step_fn(step, (jnp.ones(4),))
    assert [f.rule for f in report.findings] == ["HVD100"]
    assert "synthetic trace bomb" in report.findings[0].message


def test_plain_python_nameerror_is_hvd100_not_axis_finding():
    """A typo NameError in the user's step fn must not masquerade as an
    unbound-axis HVD101 — even when the typo'd name contains 'axis'
    (review regression)."""
    def step(x):
        return x * axis_scale  # noqa: F821

    report = check_step_fn(step, (jnp.ones(4),))
    assert [f.rule for f in report.findings] == ["HVD100"]
    assert "axis_scale" in report.findings[0].message


def test_cond_branches_with_different_scan_trip_counts_mismatch():
    """psum scanned 2x vs 5x is a different runtime collective sequence —
    the signature must expand scans by length (review regression)."""
    def scanned(n):
        def branch(z):
            def body(c, _):
                return jax.lax.psum(c, "hvd"), None
            out, _ = jax.lax.scan(body, z, None, length=n)
            return out
        return branch

    def step(x):
        return jax.lax.cond(jnp.sum(x) > 0, scanned(2), scanned(5), x)

    report = check_step_fn(step, (jnp.ones(4),), axis_env=[("hvd", N)])
    assert [f.rule for f in report.findings] == ["HVD102"]
    assert report.census["psum"]["count"] == 7  # 2 + 5, both branches


# ---------------------------------------------------------------------------
# Census mechanics
# ---------------------------------------------------------------------------

def test_census_counts_bytes_and_scan_trip_expansion():
    def step(x):
        def body(c, _):
            return jax.lax.psum(c, "hvd"), None

        y, _ = jax.lax.scan(body, x, None, length=5)
        return y + jax.lax.ppermute(
            x, "hvd", [(i, (i + 1) % N) for i in range(N)])

    report = check_step_fn(step, (jnp.ones(4, jnp.float32),),
                           axis_env=[("hvd", N)])
    assert report.ok()
    assert report.census["psum"] == {"count": 5, "bytes": 5 * 16}
    assert report.census["ppermute"] == {"count": 1, "bytes": 16}
    assert report.total_collectives() == 6
    assert report.total_bytes() == 96


def test_while_loop_counts_once_and_marks_dynamic():
    def step(x):
        def cond(c):
            return jnp.sum(c) < 100.0

        def body(c):
            return jax.lax.psum(c, "hvd")

        return jax.lax.while_loop(cond, body, x)

    report = check_step_fn(step, (jnp.ones(4),), axis_env=[("hvd", N)])
    assert report.ok()
    assert report.census["psum"]["count"] == 1
    assert report.dynamic_loops == 1


def test_shard_map_program_declares_its_own_axes(hvd8):
    """A fully wrapped jit(shard_map) step needs no axis_env: the walker
    reads the declared axes off the shard_map eqn's mesh."""
    mesh = hvd8.mesh()

    def local(x):
        return jax.lax.psum(x, "hvd")

    stepped = jax.jit(jax.shard_map(local, mesh=mesh, in_specs=P("hvd"),
                                    out_specs=P("hvd")))
    report = check_step_fn(stepped, (jnp.ones((N, 4)),), label="wrapped")
    assert report.ok(), [f.message for f in report.findings]
    assert report.census["psum"]["count"] == 1


# ---------------------------------------------------------------------------
# The DistributedOptimizer acceptance trio: clean step, census, timeline
# ---------------------------------------------------------------------------

def _opt_fixture():
    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    params = {"w": jnp.ones((3, 2), jnp.float32),
              "b": jnp.ones((2,), jnp.float32)}
    state = opt.init(params)
    grads = {"w": jnp.full((3, 2), 0.5, jnp.float32),
             "b": jnp.full((2,), 0.5, jnp.float32)}
    return opt, params, state, grads


def test_clean_distributed_optimizer_step_zero_findings(hvd8):
    opt, params, state, grads = _opt_fixture()

    def update(g):
        u, _ = opt.update(g, state, params)
        return u

    report = check_step_fn(update, (grads,),
                           axis_env=[(hvd.mesh_axis(), hvd.num_slots())],
                           label="opt_step")
    assert report.ok(), [f.message for f in report.findings]
    # One psum per gradient leaf; payload = the two leaves' f32 bytes.
    assert report.census["psum"]["count"] == 2
    assert report.census["psum"]["bytes"] == (6 + 2) * 4


def test_optimizer_census_surfaced_via_timeline(hvd8, tmp_path):
    opt, params, state, grads = _opt_fixture()

    def update(g):
        u, _ = opt.update(g, state, params)
        return u

    report = check_step_fn(update, (grads,),
                           axis_env=[(hvd.mesh_axis(), hvd.num_slots())],
                           label="opt_step")
    path = str(tmp_path / "census_timeline.json")
    tl = Timeline(path, rank=0)
    tl.collective_census("opt_step", report.census)
    tl.close()
    with open(path) as f:
        events = json.load(f)
    census_events = [e for e in events
                     if str(e.get("name", "")).startswith(
                         "COLLECTIVE_CENSUS/opt_step/")]
    assert len(census_events) == 1
    ev = census_events[0]
    assert ev["ph"] == "C"
    assert ev["name"] == "COLLECTIVE_CENSUS/opt_step/psum"
    assert ev["args"] == {"count": 2, "bytes": 32}


def test_full_training_step_census_includes_metric_allreduce(hvd8):
    """A realistic shard_step body: grads + loss-allreduce both appear."""
    opt, params, state, grads = _opt_fixture()

    def local_step(p, s, xb):
        def loss_fn(p_):
            return jnp.sum((xb @ p_["w"] + p_["b"]) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(p)
        u, s = opt.update(g, s, p)
        loss = hvd.allreduce(loss, op=hvd.Average)
        return optax.apply_updates(p, u), s, loss

    mesh = hvd8.mesh()
    mapped = jax.shard_map(local_step, mesh=mesh,
                           in_specs=(P(), P(), P("hvd")),
                           out_specs=(P(), P(), P()))
    xb = jnp.ones((N, 3), jnp.float32)
    report = check_step_fn(mapped, (params, state, xb), label="train")
    assert report.ok(), [f.message for f in report.findings]
    assert report.census["psum"]["count"] == 3  # w, b, loss


# ---------------------------------------------------------------------------
# HVD_ANALYZE=1 trace-time hook
# ---------------------------------------------------------------------------

@pytest.fixture()
def analyze_env(monkeypatch):
    monkeypatch.setenv("HVD_ANALYZE", "1")
    hook.reset()
    yield
    hook.reset()


def test_hook_shard_step_publishes_report(analyze_env, hvd8):
    opt, params, state, grads = _opt_fixture()

    def local_step(p, s, xb):
        def loss_fn(p_):
            return jnp.sum((xb @ p_["w"] + p_["b"]) ** 2)

        g = jax.grad(loss_fn)(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    _core._state.analysis_reports = []
    step = hvd.shard_step(local_step, in_specs=(P(), P(), P("hvd")),
                          out_specs=(P(), P()))
    xb = jnp.ones((N, 3), jnp.float32)
    p1, s1 = step(params, state, xb)
    p1, s1 = step(p1, s1, xb)  # second call: no re-analysis
    reports = hvd.core.analysis_reports()
    labels = [r.label for r in reports]
    assert labels == ["shard_step:local_step/3"]
    assert reports[0].ok(), [f.message for f in reports[0].findings]
    assert reports[0].census["psum"]["count"] == 2
    # And training actually trained: params moved.
    assert not np.allclose(np.asarray(p1["w"]), np.asarray(params["w"]))


def test_hook_eager_optimizer_publishes_census(analyze_env, hvd8):
    _core._state.analysis_reports = []
    opt, params, state, grads = _opt_fixture()
    updates, _ = opt.update(grads, state, params)  # eager dispatch
    reports = hvd.core.analysis_reports()
    assert len(reports) == 1
    assert reports[0].label.startswith("DistributedOptimizer:")
    assert reports[0].ok(), [f.message for f in reports[0].findings]
    # Census of the in-trace-equivalent reduction: one psum per leaf.
    assert reports[0].census["psum"]["count"] == 2
    assert reports[0].census["psum"]["bytes"] == 32
    # The hook must not alter the update's structure/results.
    assert jax.tree_util.tree_structure(updates) == \
        jax.tree_util.tree_structure(grads)
    # Analyzed once per optimizer instance: a second update is silent.
    opt.update(grads, state, params)
    assert len(hvd.core.analysis_reports()) == 1


def test_hook_never_crashes_training_on_untraceable_step(analyze_env, hvd8,
                                                         caplog):
    """Loud-but-graceful: a step that cannot be re-traced by the checker
    still runs; the failure lands in analysis_reports as HVD100."""
    _core._state.analysis_reports = []
    calls = {"n": 0}

    def flaky(x):
        # Raises only on the checker's trace (which runs FIRST, before the
        # real jit compile): the hook must swallow that and keep training.
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("refuses the analysis trace")
        return x * 2.0

    step = hvd.shard_step(flaky, in_specs=(P("hvd"),),
                          out_specs=P("hvd"))
    out = step(jnp.ones((N,)))  # must not raise
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones(N))
    reports = hvd.core.analysis_reports()
    assert len(reports) == 1
    assert [f.rule for f in reports[0].findings] == ["HVD100"]
    assert "refuses the analysis trace" in reports[0].findings[0].message


def test_hook_analyzes_same_named_distinct_steps(analyze_env, hvd8):
    """Two different step fns sharing a name+arity each get their own
    analysis (review regression: name-keyed dedup skipped the second)."""
    _core._state.analysis_reports = []

    def make(scale):
        def step(x):  # same __name__ 'step' for both instances
            return jax.lax.psum(x * scale, "hvd")
        return hvd.shard_step(step, in_specs=(P("hvd"),),
                              out_specs=P("hvd"))

    s1, s2 = make(1.0), make(2.0)
    s1(jnp.ones((N,)))
    s2(jnp.ones((N,)))
    assert len(hvd.core.analysis_reports()) == 2


def test_hook_analyzes_every_optimizer_instance(analyze_env, hvd8):
    """Each DistributedOptimizer instance is checked (review regression:
    id()-keyed dedup could skip a later instance)."""
    _core._state.analysis_reports = []
    for _ in range(2):
        opt, params, state, grads = _opt_fixture()
        opt.update(grads, state, params)
    labels = [r.label for r in hvd.core.analysis_reports()]
    assert len(labels) == 2 and labels[0] != labels[1]


def test_hook_disabled_is_inert(monkeypatch, hvd8):
    monkeypatch.delenv("HVD_ANALYZE", raising=False)
    hook.reset()
    _core._state.analysis_reports = []
    opt, params, state, grads = _opt_fixture()
    opt.update(grads, state, params)
    assert hvd.core.analysis_reports() == []
