"""KV/coordinator failover: the launcher process (which hosts the
rendezvous KV store AND the rank-0-side coordination) dies mid-run.

Contract (VERDICT r2 #8): workers must detect the dead control plane and
convert it into a bounded, NAMED failure — commit state is already on disk
(HVD_TPU_ELASTIC_SPILL_DIR spills every commit) — and a relaunched job
adopts the spill and continues from the last commit.  The launcher/KV
remains a SPOF by design (the reference's rank-0 controller is the same,
SURVEY §2.1); what this test pins down is that its death is (a) detected
within the liveness window, not the full elastic timeout, and (b)
recoverable by relaunch with zero lost commits.

Chain under test: eager dispatch KV publish raises a transport error →
Negotiator maps it to HorovodInternalError (ops/negotiation.py
_map_transport_error) → hvd.elastic.run restores the last commit and
resets → the reset's rendezvous liveness check raises
RendezvousUnreachableError (elastic/__init__.py _RendezvousLiveness) →
worker exits with the named error instead of hanging.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Serialize with the other subprocess-world e2e files (conftest
# pytest_collection_modifyitems): overlapping multi-process worlds on one
# host core cascade spurious stall timeouts.
pytestmark = pytest.mark.xdist_group("heavy_e2e")

WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys, os, time; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
state = hvd.elastic.TpuState(params={{"w": jnp.zeros((2,))}}, batch=0)
progress = {progress!r} + "." + os.environ["HOROVOD_RANK"]

@hvd.elastic.run
def train(state):
    first = state.batch
    while state.batch < 40:
        hvd.allreduce(jnp.ones((2,)), op=hvd.Sum, name="g")
        state.params = {{"w": state.params["w"] + 1.0}}
        state.batch += 1
        if state.batch % 2 == 0:
            state.commit()
        open(progress, "w").write(str(state.batch))
        time.sleep({pace})
    return first

first = train(state)
print(f"rank{{hvd.rank()}} KVDONE first_batch={{first}} "
      f"batches={{state.batch}}", flush=True)
"""


def _wait_progress(path, target, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if int(open(path).read() or 0) >= target:
                return
        except (FileNotFoundError, ValueError):
            pass
        time.sleep(0.2)
    raise AssertionError(f"no progress to batch {target} at {path}")


@pytest.mark.integration
def test_kv_server_death_is_bounded_and_relaunch_resumes(tmp_path):
    progress = str(tmp_path / "progress")
    worker = tmp_path / "worker.py"
    env = dict(os.environ)
    env["HVD_TPU_ELASTIC_SPILL_DIR"] = str(tmp_path / "spill")
    env["HVD_TPU_RENDEZVOUS_DEAD_S"] = "5"
    env["HOROVOD_GLOO_TIMEOUT_SECONDS"] = "20"
    env["HVD_TPU_DIST_SHUTDOWN_TIMEOUT_S"] = "5"

    # Run 1: slow pace so the kill lands mid-training.
    worker.write_text(WORKER.format(repo=REPO, progress=progress,
                                    pace=0.25))
    launcher = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(worker)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        _wait_progress(progress + ".0", 6)
        _wait_progress(progress + ".1", 6)
        # SIGKILL the launcher: the KV store and any cleanup die with it;
        # workers (own process groups) become orphans.
        os.kill(launcher.pid, signal.SIGKILL)
        launcher.wait(timeout=30)
    finally:
        if launcher.poll() is None:
            launcher.kill()
            launcher.wait()

    # Orphaned workers must exit within the liveness window + reset
    # overhead — NOT the 300 s negotiation / 600 s elastic timeouts.
    deadline = time.time() + 90
    while time.time() < deadline:
        r = subprocess.run(["pgrep", "-f", str(worker)],
                           capture_output=True, text=True)
        if r.returncode != 0:  # no matching processes
            break
        time.sleep(1.0)
    else:
        subprocess.run(["pkill", "-9", "-f", str(worker)])
        raise AssertionError(
            "workers still alive 90s after KV death — liveness detection "
            "failed")

    last_commit = min(int(open(progress + ".0").read()),
                      int(open(progress + ".1").read()))
    assert last_commit >= 6

    # Run 2: same spill dir — must adopt the on-disk commit, not restart
    # from scratch, and run to completion.
    worker.write_text(WORKER.format(repo=REPO, progress=progress,
                                    pace=0.0))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(worker)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    import re
    done = re.findall(r"rank(\d) KVDONE first_batch=(\d+) batches=(\d+)",
                      proc.stdout)
    assert len(done) == 2, proc.stdout[-3000:]
    for _rank, first, batches in done:
        assert int(batches) == 40
        # Adopted spill: resumed from an even (committed) batch >= 6, with
        # at most one uncommitted batch lost relative to observed progress.
        assert int(first) >= 6 and int(first) % 2 == 0, done
