"""Native C++ core tests (csrc/hvd_core.cc via ctypes).

Covers the surviving host-side logic of the reference's C++ core:
ResponseCache LRU/invalidation (response_cache.h:45), negotiation message
table with duplicate + mismatch detection (controller.cc:496,1115), fusion
planning with look-ahead (controller.cc:901), TensorQueue (tensor_queue.h:28)
and StallInspector (stall_inspector.h:30).
"""

import os
import subprocess
import sys

import pytest

from horovod_tpu import csrc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_abi_version():
    # Keep in lockstep with csrc._ABI: lib() rebuilds a stale .so by
    # comparing against it, so a drifting constant would mask real skew.
    assert csrc.lib().hvd_core_abi_version() == csrc._ABI


# -- ResponseCache -----------------------------------------------------------

def test_cache_miss_put_hit_invalid():
    c = csrc.NativeResponseCache(8)
    assert c.lookup("t", "float32", [4, 4]) == csrc.CACHE_MISS
    bit = c.put("t", "float32", [4, 4])
    assert bit == 0
    assert c.lookup("t", "float32", [4, 4]) == csrc.CACHE_HIT
    # Shape change → INVALID (forces renegotiation).
    assert c.lookup("t", "float32", [8, 4]) == csrc.CACHE_INVALID
    # Param change → INVALID too.
    assert c.lookup("t", "float32", [4, 4], prescale=0.5) == \
        csrc.CACHE_INVALID
    assert c.invalidate("t")
    assert c.lookup("t", "float32", [4, 4]) == csrc.CACHE_MISS


def test_cache_lru_eviction_and_bit_reuse():
    c = csrc.NativeResponseCache(2)
    b0 = c.put("a", "float32", [1])
    b1 = c.put("b", "float32", [1])
    assert {b0, b1} == {0, 1}
    c.lookup("a", "float32", [1])      # touch a → b becomes LRU
    b2 = c.put("c", "float32", [1])    # evicts b, reuses its bit
    assert b2 == b1
    assert c.lookup("b", "float32", [1]) == csrc.CACHE_MISS
    assert c.lookup("a", "float32", [1]) == csrc.CACHE_HIT
    assert len(c) == 2


def test_cache_zero_capacity_disabled():
    c = csrc.NativeResponseCache(0)  # HOROVOD_CACHE_CAPACITY=0
    assert c.put("t", "float32", [1]) == -1
    assert c.lookup("t", "float32", [1]) == csrc.CACHE_MISS


# -- MessageTable ------------------------------------------------------------

def test_msgtable_ready_and_validate_ok():
    mt = csrc.NativeMessageTable(3)
    assert mt.increment("g", "float32", [4], 1, rank=0) == 0
    assert mt.increment("g", "float32", [4], 1, rank=2) == 0
    assert mt.reported_ranks("g") == [0, 2]
    assert mt.increment("g", "float32", [4], 1, rank=1) == 1  # ready
    assert mt.validate("g") == ""
    mt.erase("g")
    assert mt.pending() == []


def test_msgtable_duplicate_rank():
    mt = csrc.NativeMessageTable(2)
    assert mt.increment("g", "float32", [4], 1, rank=0) == 0
    assert mt.increment("g", "float32", [4], 1, rank=0) == -1  # duplicate


def test_msgtable_shape_mismatch():
    mt = csrc.NativeMessageTable(2)
    mt.increment("g", "float32", [4], 1, rank=0)
    mt.increment("g", "float32", [5], 1, rank=1)
    assert "Mismatched shapes" in mt.validate("g")


def test_msgtable_dtype_mismatch_names_ranks():
    mt = csrc.NativeMessageTable(2)
    mt.increment("g", "float32", [4], 1, rank=0)
    mt.increment("g", "float16", [4], 1, rank=1)
    err = mt.validate("g")
    assert "Mismatched data types" in err
    assert "float32" in err and "float16" in err


def test_msgtable_allgather_ragged_dim0_allowed():
    mt = csrc.NativeMessageTable(2)
    mt.increment("g", "float32", [4, 7], 1000, rank=0)  # allgather kind
    mt.increment("g", "float32", [9, 7], 1000, rank=1)
    assert mt.validate("g") == ""
    mt2 = csrc.NativeMessageTable(2)
    mt2.increment("g", "float32", [4, 7], 1000, rank=0)
    mt2.increment("g", "float32", [9, 8], 1000, rank=1)
    assert "trailing" in mt2.validate("g")


def test_msgtable_pending_order():
    mt = csrc.NativeMessageTable(2)
    mt.increment("b", "float32", [1], 1, rank=0)
    mt.increment("a", "float32", [1], 1, rank=0)
    assert mt.pending() == ["b", "a"]  # arrival order, not alphabetical


# -- Fusion planner ----------------------------------------------------------

def test_fusion_threshold_and_lookahead():
    entries = [
        ("g0", "float32", 100, 1, 0),
        ("g1", "float16", 80, 1, 0),   # different dtype: skipped (look-ahead)
        ("g2", "float32", 120, 1, 0),  # fuses with g0 (220 <= 256)
        ("g3", "float32", 50, 1, 0),   # 270 > 256 → next bucket
        ("g4", "float16", 60, 1, 0),   # fuses with g1
    ]
    buckets = csrc.plan_fusion(entries, threshold_bytes=256)
    assert [sorted(b) for b in buckets] == [[0, 2], [1, 4], [3]]


def test_fusion_respects_process_set_and_op():
    entries = [
        ("a", "float32", 10, 1, 0),
        ("b", "float32", 10, 2, 0),  # different op
        ("c", "float32", 10, 1, 5),  # different process set
        ("d", "float32", 10, 1, 0),  # fuses with a
    ]
    buckets = csrc.plan_fusion(entries, threshold_bytes=1000)
    assert [sorted(b) for b in buckets] == [[0, 3], [1], [2]]


def test_fusion_empty():
    assert csrc.plan_fusion([], 128) == []


# -- TensorQueue -------------------------------------------------------------

def test_tensor_queue_duplicate_and_fifo():
    q = csrc.NativeTensorQueue()
    assert q.add("x", "float32", [4])
    assert not q.add("x", "float32", [4])  # duplicate in flight
    assert q.add("y", "float32", [4])
    assert len(q) == 2
    assert q.pop(10) == ["x", "y"]
    q.finish("x")
    assert q.add("x", "float32", [4])  # finished → name reusable


# -- StallInspector ----------------------------------------------------------

def test_stall_inspector_warn_and_report():
    si = csrc.NativeStallInspector(warning_time_s=1.0, shutdown_time_s=10.0,
                                   world_size=4)
    si.record_request("t", 0, now=0.0)
    si.record_request("t", 2, now=0.1)
    status, report = si.check(now=0.5)
    assert status == si.OK  # not yet past warning time
    status, report = si.check(now=2.0)
    assert status == si.WARN
    (name, waited, ready, missing), = report
    assert name == "t" and ready == [0, 2] and missing == [1, 3]
    status, _ = si.check(now=20.0)
    assert status == si.SHUTDOWN
    si.record_done("t")
    status, report = si.check(now=30.0)
    assert status == si.OK and report == []


def test_stall_inspector_complete_set_not_stalled():
    si = csrc.NativeStallInspector(1.0, 0.0, 2)
    si.record_request("t", 0, 0.0)
    si.record_request("t", 1, 0.0)
    status, report = si.check(100.0)
    assert status == si.OK  # all ranks reported → not a stall


# -- integration: negotiation catches cross-rank mismatch --------------------

MISMATCH_WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd
import jax.numpy as jnp
hvd.init()
shape = 4 if hvd.rank() == 0 else 5   # deliberate cross-rank mismatch
try:
    hvd.allreduce(jnp.ones((shape,)), name="grad.fc")
    print("NO_ERROR")
except hvd.HorovodInternalError as e:
    print("CAUGHT_MISMATCH:", str(e)[:80])
"""


@pytest.mark.integration
def test_negotiation_rejects_shape_mismatch_across_processes(tmp_path):
    """The whole point of the controller: a cross-rank shape mismatch must
    produce an error response on every rank (controller.cc:496), not an ICI
    deadlock."""
    script = tmp_path / "mismatch.py"
    script.write_text(MISMATCH_WORKER.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert proc.stdout.count("CAUGHT_MISMATCH") == 2, \
        proc.stdout + proc.stderr
    assert "Mismatched shapes" in proc.stdout
