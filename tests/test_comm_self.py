"""Self-hvdshard regression gate: the repo must stay hvdshard-clean.

The analog of tests/test_lint_self.py / test_race_self.py /
test_mem_self.py for the sharding/communication analysis
(analysis/shardplan.py): runs ``--comm`` over ``horovod_tpu/`` +
``examples/`` in-process and fails on ANY unsuppressed HVD4xx finding —
a newly introduced conflicting sharding annotation (implicit resharding)
or a dead mesh axis fails tier-1 before it wastes chips in a fleet.

To silence a deliberate pattern, add ``# hvdlint: disable=HVD40x`` on
the flagged line WITH a reasoned comment (docs/static_analysis.md).
"""

import os

from horovod_tpu.analysis import comm_paths, unsuppressed
from horovod_tpu.analysis.cli import main as cli_main

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PATHS = [os.path.join(_REPO, "horovod_tpu"),
          os.path.join(_REPO, "examples")]


def test_repo_is_hvdshard_clean():
    findings = comm_paths(_PATHS)
    active = unsuppressed(findings)
    assert not active, (
        "hvdshard found sharding/communication hazards — fix them "
        "(rebind the re-annotated name / exercise or drop the dead "
        "mesh axis) or suppress each with a reasoned "
        "'# hvdlint: disable=...' comment:\n"
        + "\n".join(f.format() for f in active))


def test_comm_suppressions_are_auditable():
    """Every suppressed hvdshard finding still surfaces with
    suppressed=True — the audit trail the dogfooding satellite
    requires."""
    for f in comm_paths(_PATHS):
        assert f.suppressed, f.format()


def test_comm_walk_covers_the_sharding_tree():
    """Guard the gate itself: the walk must actually reach the
    sharding-heavy subsystems — zero findings would mean nothing if the
    walker silently skipped the mesh/shard_step layer, the serve
    engine, or the analyzer's own module."""
    from horovod_tpu.analysis.linter import iter_python_files
    files = iter_python_files(_PATHS)
    assert len(files) > 50
    for mod in (os.path.join("parallel", "__init__.py"),
                os.path.join("parallel", "ring.py"),
                os.path.join("parallel", "tensor.py"),
                os.path.join("serve", "engine.py"),
                os.path.join("analysis", "shardplan.py")):
        assert any(f.endswith(mod) for f in files), f"{mod} not analyzed"
    assert not any("__pycache__" in f for f in files)


def test_comm_dogfood_cli_exits_zero(capsys):
    """The acceptance command, through the registry dispatch:
    python -m horovod_tpu.analysis --comm horovod_tpu examples."""
    rc = cli_main(["--comm"] + _PATHS)
    capsys.readouterr()
    assert rc == 0
