"""hvdtrace unit coverage (ISSUE 9): context propagation (HTTP + KV),
sampling on/off with the zero-overhead-off contract, shard merging with
clock-offset alignment, the bounded Timeline queue's drop accounting,
faultline trace correlation, and the per-stage latency decomposition.
"""

import json
import os
import socket
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu.obs import merge as mg
from horovod_tpu.obs import tracing as tr
from horovod_tpu.obs.cli import run_commandline as hvdtrace_cli


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts and ends tracer-less with the env bootstrap
    re-armed (mirrors faultline's test discipline)."""
    tr.uninstall()
    tr._env_checked = False
    yield
    tr.uninstall()
    tr._env_checked = False


def _mlp_scheduler(num_replicas=1, max_batch=4, **engine_kwargs):
    from horovod_tpu.models import create_mlp
    from horovod_tpu.serve import MLPAdapter, build_replicas
    vocab = 32
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, vocab)))["params"]
    return build_replicas(
        lambda: MLPAdapter(mlp, params, vocab_size=vocab, max_len=64),
        num_replicas=num_replicas, max_batch=max_batch, **engine_kwargs)


# ---------------------------------------------------------------------------
# context + sampling
# ---------------------------------------------------------------------------

def test_context_ids_headers_and_scope():
    t = tr.Tracer(sample=1.0)
    ctx = t.new_context()
    assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 8
    assert ctx.parent_id is None
    assert dict(ctx.headers()) == {"X-Trace-Id": ctx.trace_id,
                                   "X-Parent-Span": ctx.span_id}
    # Continuation keeps the trace id, records the upstream span as
    # parent, and mints a fresh span id.
    cont = t.new_context(trace_id=ctx.trace_id, parent=ctx.span_id)
    assert cont.trace_id == ctx.trace_id
    assert cont.parent_id == ctx.span_id
    assert cont.span_id != ctx.span_id
    assert tr.current() is None
    with tr.scope(ctx):
        assert tr.current() is ctx
        assert tr.current_trace_id() == ctx.trace_id
    assert tr.current() is None and tr.current_trace_id() is None


def test_env_bootstrap_off_and_on(monkeypatch):
    # Unset / 0 / garbage → no tracer (the zero-overhead default).
    for val in (None, "0", "0.0", "not-a-float"):
        tr.uninstall()
        tr._env_checked = False
        if val is None:
            monkeypatch.delenv("HVD_TRACE_SAMPLE", raising=False)
        else:
            monkeypatch.setenv("HVD_TRACE_SAMPLE", val)
        assert tr.maybe_install_from_env() is None
        assert tr.TRACER is None
    tr._env_checked = False
    monkeypatch.setenv("HVD_TRACE_SAMPLE", "0.25")
    t = tr.maybe_install_from_env()
    assert t is not None and tr.TRACER is t and t.sample == 0.25
    # One-shot: a second call returns the installed tracer, and a
    # programmatic install is never overridden.
    assert tr.maybe_install_from_env() is t


def test_sampling_probabilities():
    assert not tr.Tracer(sample=0.0).should_sample()
    assert tr.Tracer(sample=1.0).should_sample()
    t = tr.Tracer(sample=0.5)
    hits = sum(t.should_sample() for _ in range(400))
    assert 100 < hits < 300  # ~N(200, 10): 10-sigma bounds, not flaky


# ---------------------------------------------------------------------------
# shard merge + clock alignment
# ---------------------------------------------------------------------------

def _write_shard(path, label, wall_ns, mono_ns, events):
    with open(path, "w") as fh:
        fh.write(json.dumps({"type": "anchor", "label": label,
                             "pid": 1234, "rank": 0, "wall_ns": wall_ns,
                             "mono_ns": mono_ns}) + "\n")
        for ev in events:
            fh.write(json.dumps(ev) + "\n")


def test_merge_aligns_skewed_monotonic_clocks(tmp_path):
    """Two shards whose monotonic epochs differ by seconds (two
    processes) interleave correctly after wall-anchor alignment, the
    merged Chrome array is time-sorted, and the cross-shard span tree
    keeps its parentage."""
    tid = "ab" * 8
    # Shard A (server): root span [100ms, 400ms] on a mono clock whose
    # epoch maps mono 0 → wall 1_000_000_000.
    _write_shard(
        tmp_path / "trace-1234-server.jsonl", "server",
        wall_ns=1_000_000_000, mono_ns=0,
        events=[{"type": "span", "trace": tid, "span": "aaaaaaaa",
                 "parent": None, "name": "http-handle", "proc": "server",
                 "t0_ns": 100_000_000, "t1_ns": 400_000_000, "args": {}}])
    # Shard B (replica): child spans on a mono clock offset by +5s
    # (anchor says mono 5_000_000_000 == the same wall second), queue
    # [120ms, 140ms] and decode [150ms, 390ms] in aligned time.
    _write_shard(
        tmp_path / "trace-1234-replica-0.jsonl", "replica-0",
        wall_ns=1_000_000_000, mono_ns=5_000_000_000,
        events=[{"type": "span", "trace": tid, "span": "bbbbbbbb",
                 "parent": "aaaaaaaa", "name": "queue-wait",
                 "proc": "replica-0", "t0_ns": 5_120_000_000,
                 "t1_ns": 5_140_000_000, "args": {}},
                {"type": "span", "trace": tid, "span": "cccccccc",
                 "parent": "aaaaaaaa", "name": "decode",
                 "proc": "replica-0", "t0_ns": 5_150_000_000,
                 "t1_ns": 5_390_000_000, "args": {}}])
    shards = mg.load_shards(str(tmp_path))
    assert [s.label for s in shards] == ["replica-0", "server"]
    events, meta = mg.merge_chrome(shards)
    assert meta["traces"] == 1
    timed = [e for e in events if "ts" in e]
    ts = [e["ts"] for e in timed]
    assert ts == sorted(ts)  # globally monotonic by construction
    # Alignment: the root's begin (wall 1.1s) precedes the child's
    # (wall 1.12s) even though the RAW monotonic stamps say otherwise.
    begins = {e["name"]: e["ts"] for e in timed if e.get("ph") == "b"}
    assert begins["http-handle"] < begins["queue-wait"] \
        < begins["decode"]
    assert begins["queue-wait"] - begins["http-handle"] == \
        pytest.approx(20_000, abs=1)  # 20 ms in us
    # Cross-shard tree: both children hang off the server root.
    traces = mg.spans_by_trace(shards)
    tree = mg.build_tree([e for e in traces[tid]
                          if e["type"] == "span"])
    assert len(tree) == 1 and tree[0]["name"] == "http-handle"
    assert [c["name"] for c in tree[0]["children"]] == \
        ["queue-wait", "decode"]
    # Critical path sums the stage spans.
    cp = mg.critical_path(traces[tid])
    assert cp["total_ms"] == pytest.approx(300.0)
    assert cp["stages_ms"]["queue"] == pytest.approx(20.0)
    assert cp["stages_ms"]["decode"] == pytest.approx(240.0)
    assert cp["replicas"] == ["replica-0"]


def test_merge_clamps_child_before_parent_skew(tmp_path):
    """Sub-RTT wall skew can put a child's begin BEFORE its parent's —
    the tree clamp shifts it forward instead of drawing causality
    backwards, and records the shift."""
    tid = "cd" * 8
    _write_shard(
        tmp_path / "trace-1234-server.jsonl", "server",
        wall_ns=0, mono_ns=0,
        events=[{"type": "span", "trace": tid, "span": "aaaaaaaa",
                 "parent": None, "name": "http-handle", "proc": "server",
                 "t0_ns": 100_000_000, "t1_ns": 200_000_000,
                 "args": {}}])
    _write_shard(
        tmp_path / "trace-1234-replica-0.jsonl", "replica-0",
        wall_ns=0, mono_ns=0,
        events=[{"type": "span", "trace": tid, "span": "bbbbbbbb",
                 "parent": "aaaaaaaa", "name": "queue-wait",
                 "proc": "replica-0", "t0_ns": 97_000_000,
                 "t1_ns": 110_000_000, "args": {}}])
    shards = mg.load_shards(str(tmp_path))
    traces = mg.spans_by_trace(shards)
    tree = mg.build_tree(traces[tid])
    child = tree[0]["children"][0]
    assert child["wall0_ns"] == tree[0]["wall0_ns"]  # clamped, not before
    assert child["clock_clamped_ns"] == 3_000_000


def test_hvdtrace_cli_contract(tmp_path, capsys):
    assert hvdtrace_cli(["--dir", str(tmp_path / "nope")]) == 1
    empty = tmp_path / "empty"
    empty.mkdir()
    assert hvdtrace_cli(["--dir", str(empty)]) == 1
    capsys.readouterr()
    tid = "ef" * 8
    _write_shard(
        tmp_path / "trace-1234-server.jsonl", "server", 0, 0,
        [{"type": "span", "trace": tid, "span": "aaaaaaaa",
          "parent": None, "name": "http-handle", "proc": "server",
          "t0_ns": 0, "t1_ns": 50_000_000, "args": {}}])
    out = tmp_path / "merged.json"
    assert hvdtrace_cli(["--dir", str(tmp_path), "-o", str(out)]) == 0
    printed = capsys.readouterr().out
    assert tid in printed and "total=" in printed
    arr = json.load(open(out))
    assert all("ph" in e and "name" in e for e in arr)
    assert hvdtrace_cli(["--dir", str(tmp_path), "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["traces"][tid]["total_ms"] == pytest.approx(50.0)


def test_hvdtrace_folds_timeline_files(tmp_path, capsys):
    """--timeline folds an in-process Timeline chrome trace (carrying
    COMM_CENSUS counters + ELASTIC instants) into the merged fleet
    trace under a fresh pid, and meta marks it unaligned (timelines
    have no wall anchor)."""
    from horovod_tpu.timeline import Timeline
    tid = "ab" * 8
    _write_shard(
        tmp_path / "trace-1234-server.jsonl", "server", 0, 0,
        [{"type": "span", "trace": tid, "span": "aaaaaaaa",
          "parent": None, "name": "http-handle", "proc": "server",
          "t0_ns": 0, "t1_ns": 50_000_000, "args": {}}])
    tl_path = tmp_path / "rank0_timeline.json"
    tl = Timeline(str(tl_path), rank=0)
    tl.comm_census("step", {"total_wire_bytes": 4096, "dcn_wire_bytes": 0,
                            "reshard_bytes": 0, "by_primitive": {},
                            "by_axis": {}})
    tl.elastic_event("reset", 3, "refresh-world")
    tl.close()
    out = tmp_path / "merged.json"
    assert hvdtrace_cli(["--dir", str(tmp_path), "-o", str(out),
                         "--timeline", str(tl_path), "--json"]) == 0
    out_text = capsys.readouterr().out
    printed = json.loads(out_text[out_text.index("{"):])
    (tl_meta,) = printed["meta"]["timelines"]
    assert tl_meta["label"] == "timeline:rank0_timeline.json"
    assert tl_meta["aligned"] is False and tl_meta["events"] > 0
    merged = json.load(open(out))
    span_pids = {e["pid"] for e in merged
                 if e.get("name") == "http-handle"}
    comm = [e for e in merged
            if e.get("name") == "COMM_CENSUS/step" and e.get("ph") == "C"]
    elastic = [e for e in merged
               if e.get("name", "").startswith("ELASTIC/")]
    assert comm and elastic
    assert comm[0]["pid"] == tl_meta["pid"]
    assert comm[0]["pid"] not in span_pids  # own process lane
    assert comm[0]["args"]["total_wire_bytes"] == 4096
    # A missing timeline file is a usage failure, not a silent skip.
    assert hvdtrace_cli(["--dir", str(tmp_path), "--timeline",
                         str(tmp_path / "nope.json")]) == 1
    capsys.readouterr()


def test_load_timeline_events_tolerates_torn_tail(tmp_path):
    """A SIGKILLed writer leaves the chrome array unterminated — the
    loader falls back to line-wise parsing and keeps whole events."""
    p = tmp_path / "torn.json"
    p.write_text('[\n{"name": "A", "ph": "C", "ts": 1, "args": {}},\n'
                 '{"name": "B", "ph": "i", "ts": 2, "arg')
    evs = mg.load_timeline_events(str(p))
    assert [e["name"] for e in evs] == ["A"]


def test_kv_clock_anchor_roundtrip():
    """publish_clock_anchor → kv_anchors → apply_kv_anchors attaches the
    RTT skew bound the merge reports (the rendezvous-KV estimation
    path)."""
    from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer
    srv = KVStoreServer()
    port = srv.start(0)
    try:
        client = KVStoreClient("127.0.0.1", port)
        anchor = tr.publish_clock_anchor(client, "world", rank=3)
        assert anchor["rtt_ns"] > 0
        # Anchors key on HOST-QUALIFIED process identity — a bare pid
        # collides across hosts (containers are routinely all pid 1).
        proc = anchor["proc"]
        assert str(os.getpid()) in proc and proc != str(os.getpid())
        anchors = mg.kv_anchors(client)
        assert anchors[proc]["label"] == "world"
        shard = mg.Shard("trace-x-world.jsonl", None, [])
        mg.apply_kv_anchors([shard], anchors)
        assert shard.anchor is not None  # backfilled
        assert shard.rtt_ns == anchors[proc]["rtt_ns"]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# HTTP propagation + /trace + stage metrics
# ---------------------------------------------------------------------------

def _post(port, body_obj, headers=()):
    body = json.dumps(body_obj).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST",
        headers=dict({"Content-Type": "application/json"}, **dict(headers)))
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), e.headers


def test_http_propagation_echo_and_trace_endpoint():
    """Inbound X-Trace-Id is continued and echoed on 200 AND on the
    400/503 sheds (the chaos-correlation satellite), the span tree
    lands in /trace with http-handle as root, and the shed debug line
    carries the trace id."""
    import logging
    import urllib.error  # noqa: F401 - used via _post
    from horovod_tpu.serve import ServeServer
    tr.install(tr.Tracer(sample=1.0))
    sched = _mlp_scheduler(num_replicas=2)
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    try:
        tid = "feedfacefeedface"
        status, out, hdrs = _post(port, {"tokens": [1, 2, 3],
                                         "max_new_tokens": 4},
                                  [("X-Trace-Id", tid),
                                   ("X-Parent-Span", "12345678")])
        assert status == 200 and len(out["tokens"]) == 4
        assert hdrs.get("X-Trace-Id") == tid
        # 400 (malformed body) echoes too.
        status, _, hdrs = _post(port, {"tokens": []},
                                [("X-Trace-Id", tid)])
        assert status == 400 and hdrs.get("X-Trace-Id") == tid
        # /trace serves the sampled span tree, rooted at http-handle
        # with the inbound parent preserved.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace", timeout=30) as resp:
            payload = json.loads(resp.read())
        assert payload["enabled"] and payload["sample"] == 1.0
        tree = next(t["tree"] for t in payload["traces"]
                    if t["trace_id"] == tid)
        roots = [n for n in tree if n["name"] == "http-handle"]
        assert roots and roots[0]["parent"] == "12345678"
        names = {c["name"] for c in roots[0]["children"]}
        assert {"route", "queue-wait", "decode"} <= names
        assert all(c["parent"] == roots[0]["span"]
                   for c in roots[0]["children"])
        # Shed echo + trace-id'd debug line: kill the fleet → 503.
        # (The repo logger sets propagate=False, so capture with a
        # handler attached to it directly rather than caplog.)
        sched.mark_dead("replica-0")
        sched.mark_dead("replica-1")
        from horovod_tpu.utils import get_logger
        records = []

        class _Capture(logging.Handler):
            def emit(self, rec):
                records.append(rec.getMessage())

        logger = get_logger()
        handler = _Capture(level=logging.DEBUG)
        old_level = logger.level
        logger.addHandler(handler)
        logger.setLevel(logging.DEBUG)
        try:
            status, _, hdrs = _post(port, {"tokens": [1]},
                                    [("X-Trace-Id", tid)])
        finally:
            logger.removeHandler(handler)
            logger.setLevel(old_level)
        assert status == 503 and hdrs.get("X-Trace-Id") == tid
        assert any(tid in msg and "outcome=" in msg for msg in records)
    finally:
        server.stop()
        tr.uninstall()


def test_malicious_inbound_trace_id_is_dropped():
    """Inbound trace ids are client input echoed into response headers
    and forwarded onto KV requests: CRLF / non-ascii / oversized ids are
    treated as absent (no echo, no continuation) — never injected."""
    from horovod_tpu.serve.server import _ServeHandler
    assert _ServeHandler._safe_id("feedface-01.x_Y") == "feedface-01.x_Y"
    for bad in (None, "", "evil\r\nX-Injected: 1", "id with spaces",
                "ünïcode", "x" * 129):
        assert _ServeHandler._safe_id(bad) is None
    from horovod_tpu.serve import ServeServer
    tr.install(tr.Tracer(sample=0.0))  # tracer on, nothing sampled
    sched = _mlp_scheduler()
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    try:
        status, _, hdrs = _post(port, {"tokens": [2], "max_new_tokens": 2},
                                [("X-Trace-Id", "bad id with spaces")])
        assert status == 200
        assert hdrs.get("X-Trace-Id") is None
        assert hdrs.get("X-Injected") is None
    finally:
        server.stop()
        tr.uninstall()


def test_front_end_sampling_decision_is_never_rerolled():
    """A request that LOST the HTTP front-end's sampling roll must not
    be re-sampled by the scheduler: re-rolling would raise the
    effective rate to 2p-p² and trace requests whose responses carry
    no X-Trace-Id.  Front-end-less submits still sample."""
    from horovod_tpu.serve import Request, ServeServer
    t = tr.install(tr.Tracer(sample=0.5))
    rolls = {"n": 0}

    def always_lose():
        rolls["n"] += 1
        return False

    t.should_sample = always_lose
    sched = _mlp_scheduler()
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    try:
        status, _, hdrs = _post(port, {"tokens": [1], "max_new_tokens": 2})
        assert status == 200 and hdrs.get("X-Trace-Id") is None
        assert rolls["n"] == 1  # the front-end rolled; the scheduler didn't
        # Direct (front-end-less) ingress still owns its own roll.
        r = Request([2], max_new_tokens=2)
        sched.submit(r)
        r.result(timeout=60)
        assert rolls["n"] == 2 and r.trace is None
    finally:
        server.stop()
        tr.uninstall()


def test_untraced_requests_still_echo_inbound_trace_id():
    """Tracer absent (sample=0 — the default): no spans, no Request
    contexts, but an inbound X-Trace-Id still echoes so upstream
    correlation survives an untraced hop."""
    from horovod_tpu.serve import ServeServer
    assert tr.TRACER is None
    sched = _mlp_scheduler()
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    try:
        status, out, hdrs = _post(port, {"tokens": [5], "max_new_tokens": 2},
                                  [("X-Trace-Id", "cafecafecafecafe")])
        assert status == 200
        assert hdrs.get("X-Trace-Id") == "cafecafecafecafe"
        status, _, hdrs = _post(port, {"tokens": [3], "max_new_tokens": 2})
        assert status == 200 and hdrs.get("X-Trace-Id") is None
    finally:
        server.stop()


def test_stage_partition_sums_to_e2e_latency():
    """The always-on stage decomposition is an EXACT partition of
    [submit, completion]: queue + prefill + decode + retry equals the
    request's end-to-end latency, and the hvd_serve_stage_ms histograms
    land on /metrics render + snapshot."""
    from horovod_tpu.serve import Request
    sched = _mlp_scheduler()
    sched.start()
    try:
        r = Request([1, 2, 3], max_new_tokens=6)
        sched.submit(r)
        r.result(timeout=60)
        e2e_ms = (time.monotonic() - r.submitted_at) * 1e3
        total = sum(r.stage_ms.values())
        assert 0 < total <= e2e_ms + 1e-6
        assert total >= e2e_ms - 50  # result() wakeup slack only
        snap = sched.metrics.snapshot()
        assert snap["stage"]["queue"]["count"] == 1
        assert snap["stage"]["decode"]["count"] == 1
        assert snap["stage"]["retry"]["count"] == 0
        text = sched.metrics.render()
        assert 'hvd_serve_stage_ms_bucket{stage="queue",le="1"}' in text
        assert 'hvd_serve_stage_ms_count{stage="decode"} 1' in text
    finally:
        sched.stop()


def test_scheduler_sampling_emits_root_and_decode_spans():
    """Front-end-less ingress (bench storms): the scheduler samples and
    the engine emits the root 'request' span at completion, so direct
    submits trace end-to-end without HTTP."""
    from horovod_tpu.serve import Request
    t = tr.install(tr.Tracer(sample=1.0))
    sched = _mlp_scheduler()
    sched.start()
    try:
        r = Request([1, 2], max_new_tokens=4)
        sched.submit(r)
        r.result(timeout=60)
        assert r.trace is not None
        recent = t.recent_traces()
        tree = next(x["tree"] for x in recent
                    if x["trace_id"] == r.trace.trace_id)
        root = next(n for n in tree if n["name"] == "request")
        assert {c["name"] for c in root["children"]} >= \
            {"queue-wait", "decode"}
    finally:
        sched.stop()
        tr.uninstall()


# ---------------------------------------------------------------------------
# KV client propagation + retry spans + faultline correlation
# ---------------------------------------------------------------------------

def _capture_server():
    """Minimal HTTP responder capturing raw request bytes (header
    assertions against the hand-rolled KV client writer)."""
    captured = []
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)

    def loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                data = conn.recv(65536)
                captured.append(data)
                conn.sendall(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Length: 0\r\n\r\n")

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return srv, srv.getsockname()[1], captured


def test_kv_client_injects_trace_headers_only_under_scope():
    from horovod_tpu.runner.http_server import KVStoreClient
    srv, port, captured = _capture_server()
    try:
        t = tr.install(tr.Tracer(sample=1.0))
        client = KVStoreClient("127.0.0.1", port)
        ctx = t.new_context()
        with tr.scope(ctx):
            client.put("s", "k", b"v")
        assert f"X-Trace-Id: {ctx.trace_id}".encode() in captured[-1]
        assert f"X-Parent-Span: {ctx.span_id}".encode() in captured[-1]
        client2 = KVStoreClient("127.0.0.1", port)  # fresh socket
        client2.put("s", "k2", b"v")  # no active scope
        assert b"X-Trace-Id" not in captured[-1]
    finally:
        tr.uninstall()
        srv.close()


def test_kv_retry_spans_and_faultline_trace_correlation():
    """A drop-kv-response train inside a traced scope: each retry
    attempt becomes a kv-retry span in the request's tree, and the
    faultline firing log + FAULTLINE instants carry the trace id (the
    chaos-correlation satellite)."""
    import horovod_tpu.faultline as fl
    from horovod_tpu.faultline import runtime as flrt
    from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer
    srv = KVStoreServer()
    port = srv.start(0)
    t = tr.install(tr.Tracer(sample=1.0))
    # Target THIS test's client instance: a target-less spec fires at
    # whichever instance's counter reaches the step first, and a
    # leftover background poller from an earlier test (preempt watcher,
    # data service) can steal a firing from the repeat window.
    plan = flrt.install(fl.FaultPlan([
        fl.FaultSpec("drop-kv-response", step=0, repeat=2,
                     target=f"127.0.0.1:{port}")], seed=7))
    try:
        client = KVStoreClient("127.0.0.1", port)
        ctx = t.new_context()
        with tr.scope(ctx):
            client.put("scope", "key", b"value")  # retries through drops
        recs = t.recent_traces()
        spans = []

        def walk(n):
            spans.append(n)
            for c in n["children"]:
                walk(c)
        for item in recs:
            for r in item["tree"]:
                walk(r)
        retries = [s for s in spans if s["name"] == "kv-retry"]
        assert len(retries) == 2
        assert [s["args"]["attempt"] for s in retries] == [1, 2]
        assert all(s["proc"] == "kv-client" for s in retries)
        assert all(s["trace"] == ctx.trace_id for s in retries)
        # Firing log correlation.
        assert all(e["trace_id"] == ctx.trace_id for e in plan.log)
        # Outside any scope the correlation is None, not garbage.
        plan2 = flrt.install(fl.FaultPlan([
            fl.FaultSpec("slow-decode", step=0)], seed=1))
        plan2.fire("engine.step", "replica-0")
        assert plan2.log[-1]["trace_id"] is None
    finally:
        flrt.uninstall()
        tr.uninstall()
        srv.stop()


# ---------------------------------------------------------------------------
# bounded Timeline queue
# ---------------------------------------------------------------------------

def test_timeline_bounded_queue_counts_drops(tmp_path):
    """The writer-queue bound: with the writer stalled, events past the
    cap drop and are COUNTED — in dropped_events, in the trace's closing
    counter event, and on the serve /metrics render."""
    from horovod_tpu.serve import ServeMetrics
    from horovod_tpu.timeline import Timeline
    path = str(tmp_path / "tl.json")
    tl = Timeline(path, queue_cap=4)
    # Stall the writer deterministically: the sentinel makes it exit,
    # so nothing drains the queue.
    tl._queue.put(None)
    tl._writer.join(timeout=10)
    assert not tl._writer.is_alive()
    for i in range(10):
        tl.serve_counter("engine", {"i": i})
    assert tl.dropped_events == 6  # 10 events into 4 slots
    m = ServeMetrics()
    m.set_timeline(tl)
    assert "hvd_timeline_dropped_events_total 6" in m.render()
    tl.close()
    events = json.load(open(path))
    trailer = events[-1]
    assert trailer["name"] == "hvd_timeline_dropped_events_total"
    # close() discards ONE queued (never-written) event to guarantee
    # the shutdown sentinel fits a full queue — that discard is a real
    # drop and is counted as one.
    assert trailer["args"]["dropped"] == 7

    # An unreadable drop counter is OMITTED from /metrics, never faked
    # as -1 (an invalid negative Prometheus counter value).
    class _Broken:
        @property
        def dropped_events(self):
            raise RuntimeError("torn down")
    m2 = ServeMetrics()
    m2.set_timeline(_Broken())
    assert "hvd_timeline_dropped_events_total" not in m2.render()


def test_timeline_queue_cap_env(tmp_path, monkeypatch):
    from horovod_tpu.timeline import Timeline
    monkeypatch.setenv("HVD_TIMELINE_QUEUE_CAP", "32")
    tl = Timeline(str(tmp_path / "tl2.json"))
    assert tl._queue.maxsize == 32
    tl.close()
    # Default run: no drops, trailer says 0.
    events = json.load(open(tmp_path / "tl2.json"))
    assert events[-1]["args"]["dropped"] == 0


def test_timeline_trace_span_rendering(tmp_path):
    """Timeline renders tracer spans as async b/e pairs and flows as
    s/t/f under the hvdtrace cats, on its own time axis."""
    from horovod_tpu.timeline import Timeline
    path = str(tmp_path / "tl3.json")
    tl = Timeline(path)
    t0 = time.monotonic_ns()
    tl.trace_span("ab" * 8, "decode", "replica-0", t0, 1000.0,
                  args={"tokens": 4})
    tl.trace_flow("ab" * 8, "token-stream", "replica-0", "s")
    tl.trace_flow("ab" * 8, "token-stream", "replica-0", "f")
    tl.trace_instant("ab" * 8, "resubmit", "replica-1",
                     args={"from": "replica-1"})
    tl.close()
    events = json.load(open(path))
    spans = [e for e in events if e.get("cat") == "hvdtrace"]
    assert [e["ph"] for e in spans] == ["b", "e"]
    assert spans[1]["ts"] - spans[0]["ts"] == pytest.approx(1000.0)
    flows = [e for e in events if e.get("cat") == "hvdtrace-flow"]
    assert [e["ph"] for e in flows] == ["s", "f"]
    assert flows[1]["bp"] == "e"
    inst = next(e for e in events
                if e["name"] == "hvdtrace/resubmit")
    assert inst["args"]["trace_id"] == "ab" * 8


def test_recent_buffer_is_bounded():
    t = tr.Tracer(sample=1.0, recent=4)
    for i in range(10):
        ctx = t.new_context()
        t.emit_span(ctx, "request", 0.0, 0.001, "server", root=True)
    assert len(t.recent_traces(limit=100)) == 4
