"""Expert-parallel MoE tests (parallel/moe.py).

Pattern per SURVEY.md §4: compute the expected value with a local NumPy/JAX
model and compare per shard; sharded-vs-unsharded equivalence on the 8-device
CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel.moe import expert_parallel_ffn

N = 8


def _mk(seed, T=16, d=8, f=16, E=8):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(T, d), jnp.float32)
    gate = jnp.asarray(rng.randn(d, E) * 2.0, jnp.float32)
    w_in = jnp.asarray(rng.randn(E, d, f) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.randn(E, f, d) * 0.1, jnp.float32)
    return x, gate, w_in, w_out


def test_moe_top1_matches_per_token_expert():
    """With top_k=1 and ample capacity, every token's output must be its
    argmax expert's FFN applied to it (weight 1 after renormalization)."""
    x, gate, w_in, w_out = _mk(0)
    res = expert_parallel_ffn(x, gate, w_in, w_out, axis_name=None,
                              top_k=1, capacity_factor=8.0)
    choice = np.argmax(np.asarray(x @ gate), axis=-1)
    for t in range(x.shape[0]):
        e = choice[t]
        want = np.asarray(jax.nn.gelu(x[t] @ w_in[e]) @ w_out[e])
        np.testing.assert_allclose(np.asarray(res.out[t]), want,
                                   rtol=1e-4, atol=1e-5)
    assert float(res.dropped_frac) == 0.0


def test_moe_top2_weights_sum():
    """top_k=2: output is the prob-renormalized blend of the two chosen
    experts' outputs."""
    x, gate, w_in, w_out = _mk(1, T=8, E=4)
    res = expert_parallel_ffn(x, gate, w_in, w_out, axis_name=None,
                              top_k=2, capacity_factor=8.0)
    probs = np.asarray(jax.nn.softmax(x @ gate, axis=-1))
    for t in range(x.shape[0]):
        top2 = np.argsort(-probs[t])[:2]
        w = probs[t][top2] / probs[t][top2].sum()
        want = sum(w[i] * np.asarray(jax.nn.gelu(x[t] @ w_in[e]) @ w_out[e])
                   for i, e in enumerate(top2))
        np.testing.assert_allclose(np.asarray(res.out[t]), want,
                                   rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
    """Capacity 1 per expert with many tokens on one expert: overflow slots
    drop (zero output rows for top_k=1), dropped_frac reports it."""
    T, d = 12, 4
    x = jnp.ones((T, d), jnp.float32)           # identical tokens
    gate = jnp.zeros((d, 2), jnp.float32).at[0, 0].set(5.0)  # all -> e0
    rng = np.random.RandomState(2)
    w_in = jnp.asarray(rng.randn(2, d, 8) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.randn(2, 8, d) * 0.1, jnp.float32)
    res = expert_parallel_ffn(x, gate, w_in, w_out, axis_name=None,
                              top_k=1, capacity_factor=1.0 / 6.0)
    # capacity = max(1, 1/6 * 1 * 12 / 2) = 1 -> one token kept
    kept_rows = np.abs(np.asarray(res.out)).sum(axis=1) > 0
    assert kept_rows.sum() == 1
    np.testing.assert_allclose(float(res.dropped_frac), 11 / 12, rtol=1e-6)


def test_moe_sharded_matches_unsharded(hvd8):
    """8-way expert parallelism (1 expert/shard, tokens sharded) must
    reproduce the unsharded math when nothing is capacity-dropped."""
    T, d, f, E = 64, 8, 16, 8
    x, gate, w_in, w_out = _mk(3, T=T, d=d, f=f, E=E)
    ref = expert_parallel_ffn(x, gate, w_in, w_out, axis_name=None,
                              top_k=2, capacity_factor=16.0)
    mesh = hvd8.mesh()

    def local(xs, gates, wi, wo):
        res = expert_parallel_ffn(xs, gates, wi, wo, axis_name="hvd",
                                  top_k=2, capacity_factor=16.0)
        return res.out, jax.lax.pmax(res.dropped_frac, "hvd")

    out, dropped = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P("hvd"), P(), P("hvd"), P("hvd")),
        out_specs=(P("hvd"), P())))(x, gate, w_in, w_out)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref.out),
                               rtol=1e-4, atol=1e-5)
    assert float(jnp.max(dropped)) == 0.0


def test_moe_aux_loss_balanced_vs_skewed():
    """The Switch aux loss must be ~1 for a uniform router and larger for
    a collapsed one."""
    x, _, w_in, w_out = _mk(4, T=64, E=8)
    uniform_gate = jnp.zeros((x.shape[1], 8), jnp.float32)
    skewed_gate = uniform_gate.at[:, 0].set(9.0)
    res_u = expert_parallel_ffn(x, uniform_gate, w_in, w_out,
                                axis_name=None, top_k=1,
                                capacity_factor=8.0)
    res_s = expert_parallel_ffn(x, skewed_gate, w_in, w_out,
                                axis_name=None, top_k=1,
                                capacity_factor=8.0)
    assert float(res_s.aux_loss) > 2.0 * float(res_u.aux_loss)
    assert 0.5 < float(res_u.aux_loss) < 2.0


def test_moe_transformer_trains(hvd8):
    """A tiny MoE transformer (2 experts, every 2nd block) trains: loss +
    sown aux loss decrease under the DistributedOptimizer step."""
    import dataclasses
    import optax
    from horovod_tpu.models import Transformer, TransformerConfig, lm_loss
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            d_model=32, d_ff=64, max_len=16, causal=True,
                            dtype=jnp.float32, moe_experts=2,
                            moe_capacity_factor=4.0)
    model = Transformer(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    assert "moe_gate" in params["params"]["block_1"]
    assert "fc1" in params["params"]["block_0"]  # alternation
    opt = hvd.DistributedOptimizer(optax.adam(1e-2))
    opt_state = opt.init(params)

    def local_step(params, opt_state, toks):
        def loss_fn(p):
            logits, mut = model.apply(p, toks, mutable=["losses"])
            aux = sum(jax.tree.leaves(mut["losses"]))
            return lm_loss(logits, toks) + 0.01 * aux
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            hvd.allreduce(loss, op=hvd.Average)

    step = hvd.parallel.shard_step(
        local_step, in_specs=(P(), P(), P("hvd")),
        out_specs=(P(), P(), P()))
    losses = []
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_transformer_expert_sharded_matches_replicated(hvd8):
    """cfg.expert_axis='hvd': the same params, with expert dims sharded by
    in_specs, must produce the replicated model's logits (ample capacity)."""
    import dataclasses
    from horovod_tpu.models import Transformer, TransformerConfig
    base = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                             d_model=32, d_ff=64, max_len=16, causal=True,
                             dtype=jnp.float32, moe_experts=8,
                             moe_capacity_factor=16.0)
    cfg_ep = dataclasses.replace(base, expert_axis="hvd")
    model_r = Transformer(base)
    model_s = Transformer(cfg_ep)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, (8, 16)))
    params = model_r.init(jax.random.PRNGKey(0), tokens)
    ref = model_r.apply(params, tokens)

    def ep_spec(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return P("hvd") if name in ("moe_w_in", "moe_w_out") else P()

    specs = jax.tree_util.tree_map_with_path(ep_spec, params)
    mesh = hvd8.mesh()
    out = jax.jit(jax.shard_map(
        lambda p, t: model_s.apply(p, t), mesh=mesh,
        in_specs=(specs, P("hvd")), out_specs=P("hvd")))(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
