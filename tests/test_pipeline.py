"""Pipeline (parallel/pipeline.py) and tensor (parallel/tensor.py)
parallelism tests: sharded-vs-sequential equivalence on the 8-device CPU
mesh, forward AND backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.compat import has_vma_tracking
from horovod_tpu.parallel.pipeline import gpipe_spmd, stack_stage_params
from horovod_tpu.parallel.tensor import (column_row_parallel_mlp,
                                         shard_columns, shard_rows)

S = 8  # stages / shards

# Gradients THROUGH in-jit collectives (psum/ppermute chains) follow the
# Horovod gradient table only under vma tracking; the old-jax transpose
# re-sums replicated cotangents (see horovod_tpu/compat.py).
requires_vma_grads = pytest.mark.skipif(
    not has_vma_tracking(),
    reason="collective gradient semantics require jax vma tracking "
           "(unavailable on this jax; see horovod_tpu/compat.py)")


def _mesh(axis):
    return Mesh(np.asarray(jax.devices()[:S]), (axis,))


def _stages(seed, d=6):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(d, d) * 0.5, jnp.float32)
            for _ in range(S)]


def _sequential(ws, xs):
    y = xs
    for w in ws:
        y = jnp.tanh(y @ w)
    return y


def test_gpipe_matches_sequential_forward():
    M, mb, d = 5, 3, 6
    ws = _stages(0, d)
    xs = jnp.asarray(np.random.RandomState(1).randn(M, mb, d), jnp.float32)
    want = _sequential(ws, xs)

    def stage_fn(p, x):
        return jnp.tanh(x @ p[0])   # local stage slice keeps leading dim 1

    def body(stacked, xs):
        return gpipe_spmd(stage_fn, stacked, xs, axis_name="pp")

    out = jax.jit(jax.shard_map(
        body, mesh=_mesh("pp"), in_specs=(P("pp"), P()),
        out_specs=P()))(stack_stage_params(ws), xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@requires_vma_grads
def test_gpipe_gradients_match_sequential():
    """jax.grad through the scan/ppermute schedule must equal the serial
    model's per-stage gradients (scan+ppermute transpose = the reverse
    pipeline schedule)."""
    M, mb, d = 4, 2, 5
    ws = _stages(2, d)
    xs = jnp.asarray(np.random.RandomState(3).randn(M, mb, d), jnp.float32)
    tgt = jnp.asarray(np.random.RandomState(4).randn(M, mb, d), jnp.float32)

    def serial_loss(stacked):
        y = xs
        for s in range(S):
            y = jnp.tanh(y @ stacked[s])
        return jnp.mean((y - tgt) ** 2)

    def stage_fn(p, x):
        return jnp.tanh(x @ p[0])

    def pipe_loss(stacked, xs, tgt):
        ys = gpipe_spmd(stage_fn, stacked, xs, axis_name="pp")
        return jnp.mean((ys - tgt) ** 2)

    stacked = stack_stage_params(ws)
    want = jax.grad(serial_loss)(stacked)

    def body(stacked, xs, tgt):
        g = jax.grad(pipe_loss)(stacked, xs, tgt)
        return g  # [1, d, d] per shard -> reassembled over 'pp'

    got = jax.jit(jax.shard_map(
        body, mesh=_mesh("pp"), in_specs=(P("pp"), P(), P()),
        out_specs=P("pp")))(stacked, xs, tgt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-6)


def test_column_row_parallel_mlp_matches_dense():
    d, f, b = 6, 32, 4
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(b, d), jnp.float32)
    w1 = jnp.asarray(rng.randn(d, f) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.randn(f, d) * 0.3, jnp.float32)
    want = jax.nn.gelu(x @ w1) @ w2

    cols = jnp.stack(shard_columns(w1, S))   # [S, d, f/S]
    rows = jnp.stack(shard_rows(w2, S))      # [S, f/S, d]

    def body(x, c, r):
        return column_row_parallel_mlp(x, c[0], r[0], axis_name="tp")

    out = jax.jit(jax.shard_map(
        body, mesh=_mesh("tp"), in_specs=(P(), P("tp"), P("tp")),
        out_specs=P()))(x, cols, rows)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


@requires_vma_grads
def test_column_row_parallel_grads_match_dense():
    d, f, b = 4, 16, 3
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(b, d), jnp.float32)
    w1 = jnp.asarray(rng.randn(d, f) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.randn(f, d) * 0.3, jnp.float32)

    def dense_loss(w1, w2):
        return jnp.sum(jax.nn.gelu(x @ w1) @ w2)

    gw1, gw2 = jax.grad(dense_loss, argnums=(0, 1))(w1, w2)

    def body(x, c, r):
        def loss(c0, r0):
            # Replicated scalar; its grad w.r.t. THIS shard's weight
            # slices equals the dense gradient's corresponding blocks
            # (other shards' partial sums are independent of them).
            return jnp.sum(column_row_parallel_mlp(x, c0, r0,
                                                   axis_name="tp"))
        gc, gr = jax.grad(loss, argnums=(0, 1))(c[0], r[0])
        return gc[None], gr[None]

    gc, gr = jax.jit(jax.shard_map(
        body, mesh=_mesh("tp"), in_specs=(P(), P("tp"), P("tp")),
        out_specs=(P("tp"), P("tp"))))(x, jnp.stack(shard_columns(w1, S)),
                                       jnp.stack(shard_rows(w2, S)))
    np.testing.assert_allclose(
        np.asarray(gc).transpose(1, 0, 2).reshape(d, f), np.asarray(gw1),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gr).reshape(f, d),
                               np.asarray(gw2), rtol=1e-4, atol=1e-5)
