"""ISSUE 8: Pallas paged-attention decode kernel + quantized KV blocks.

Pins the tentpole's contracts layer by layer:

* kernel unit parity — ``paged_decode_attention`` /
  ``paged_prefill_attention`` vs the gather reference across every mask
  mode, block_tokens ∈ {8, 16}, pool geometries and table widths (the
  online softmax associates reductions blockwise, so parity is pinned at
  flash-kernel tolerance, and at exact token-stream level through the
  engine);
* the clip-mode hole hazard — ``jnp.take(..., mode="clip")`` clamps the
  hole sentinel onto the last REAL pool block, so correctness silently
  depends on the validity mask covering every clamped entry: a poisoned
  pool (garbage written into block NB-1) must leave outputs unchanged in
  BOTH impls, so a future mask regression fails loudly instead of
  corrupting decodes;
* engine parity — ``HVD_SERVE_ATTN_IMPL=kernel`` token streams equal the
  gather engine's bit-for-bit across block-boundary prompt lengths
  (k·BT, k·BT±1), jit-bucket transitions, chunked prefill, and the
  recovery paths (poisoned batch, pool-exhaustion preemption);
* quantized KV — int8 logit error within pinned cosine/abs tolerance vs
  bf16 storage, batched==single inside the int8 engine, prefix-cache
  hashing (token-content based) unaffected by storage dtype, and the
  bytes-per-block accounting the fixed-budget bench arm is built on;
* export surfaces — kv_bytes_per_token / attention-impl / kv-dtype
  gauges in the Prometheus exposition, replica ``to_dict``.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.serve import (InferenceEngine, Request, ServeMetrics,
                               TransformerAdapter)
from horovod_tpu.serve import paged_attention as pa

BT = 8

_TINY = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_len=64, causal=True,
                          dtype=jnp.float32, scan_layers=False)


def _tiny():
    model = Transformer(_TINY)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _flax_greedy(model, params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        lg = model.apply({"params": params}, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(lg[0, -1])))
    return seq[len(prompt):]


def _engine(params, impl, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_chunk", 5)  # deliberately unaligned with BT
    ad = TransformerAdapter(_TINY, params, block_tokens=BT, attn_impl=impl,
                            kv_dtype=kw.pop("kv_dtype", None))
    return InferenceEngine(ad, kv_mode="paged",
                           replica_id=f"pa-{impl}", **kw)


def _rand_pool(rng, NB, bt, H, Dh):
    return (jnp.asarray(rng.randn(NB, bt, H, Dh).astype(np.float32)),
            jnp.asarray(rng.randn(NB, bt, H, Dh).astype(np.float32)))


# -- kernel unit parity -------------------------------------------------------

@pytest.mark.parametrize("bt", [8, 16])
@pytest.mark.parametrize("geometry", [(6, 4), (9, 7), (3, 2)])
def test_decode_kernel_matches_gather_reference(bt, geometry):
    """Decode kernel vs the gather reference across pool sizes, table
    widths, and positions straddling block boundaries (k·BT, k·BT±1) —
    including hole-sentinel tables and an inactive (pos=0, all-hole)
    row, at flash-kernel tolerance."""
    NB, MB = geometry
    H, Dh = 2, 16
    rng = np.random.RandomState(NB * bt)
    kp, vp = _rand_pool(rng, NB, bt, H, Dh)
    B = 4
    q = jnp.asarray(rng.randn(B, H, Dh).astype(np.float32))
    tables = np.full((B, MB), NB, np.int32)
    perm = rng.permutation(NB)
    positions = []
    for b, pos in enumerate([bt - 1, bt, min(bt + 1, MB * bt - 1), 0]):
        nblk = pos // bt + 1
        tables[b, :min(nblk, NB)] = perm[:min(nblk, NB)]
        positions.append(pos)
    tables[3, :] = NB  # inactive row: all holes, pos 0
    positions = jnp.asarray(positions, jnp.int32)
    tables = jnp.asarray(tables)
    out = pa.paged_decode_attention(q, kp, vp, tables, positions)
    ref = pa.paged_attention_reference(q, kp, vp, tables, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("mask_mode",
                         [pa.MASK_NONE, pa.MASK_CAUSAL, pa.MASK_STRICT])
def test_prefill_kernel_matches_gather_reference_all_mask_modes(mask_mode):
    """Chunked-prefill kernel vs the gather reference under every mask
    mode of the shared machinery (the engine uses MASK_CAUSAL; STRICT
    and NONE stay available to ring-style consumers)."""
    NB, bt, MB, H, Dh, B, C = 6, 8, 4, 2, 16, 3, 5
    rng = np.random.RandomState(mask_mode)
    kp, vp = _rand_pool(rng, NB, bt, H, Dh)
    q = jnp.asarray(rng.randn(B, C, H, Dh).astype(np.float32))
    # Block NB-1 is deliberately referenced by NO table entry: every
    # read of it is a clamped hole, so the poisoned-pool invariance
    # check below can poison it without touching legitimate keys.
    tables = jnp.asarray(
        np.array([[0, 2, NB, NB], [1, 3, 4, NB], [2, NB, NB, NB]],
                 np.int32))
    starts = jnp.asarray(np.array([7, 15, 0], np.int32))
    out = pa.paged_prefill_attention(q, kp, vp, tables, starts,
                                     mask_mode=mask_mode)
    ref = pa.paged_attention_reference(q, kp, vp, tables, starts,
                                       mask_mode=mask_mode)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    if mask_mode == pa.MASK_STRICT:
        # Review finding: a row with EVERY key masked (row 2's first
        # query sits at absolute position 0 — strict mode attends
        # nothing) must contribute exactly 0 in BOTH impls, not a
        # weight-1 average of masked garbage (exp(NEG_INF - NEG_INF)
        # == 1 without the online-softmax floor).
        assert float(jnp.max(jnp.abs(out[2, 0]))) == 0.0
        assert float(jnp.max(jnp.abs(ref[2, 0]))) == 0.0
    # Review finding: hole sentinels are never real keys in ANY mask
    # mode — under MASK_NONE the positional mask doesn't cover them, so
    # both impls must mask holes by table entry: outputs are invariant
    # to the clamped block's contents.
    kp2 = kp.at[NB - 1].set(1e30)
    vp2 = vp.at[NB - 1].set(-1e30)
    out2 = pa.paged_prefill_attention(q, kp2, vp2, tables, starts,
                                      mask_mode=mask_mode)
    ref2 = pa.paged_attention_reference(q, kp2, vp2, tables, starts,
                                        mask_mode=mask_mode)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(out))
    np.testing.assert_array_equal(np.asarray(ref2), np.asarray(ref))


def test_quantized_kernel_matches_quantized_gather_and_error_bound():
    """int8 (and fp8 where the build has it): the kernel's fused
    dequantization matches the dequantizing gather at kernel tolerance,
    and quantized attention stays within a pinned error of exact."""
    NB, bt, MB, H, Dh, B = 6, 8, 4, 4, 32, 3
    rng = np.random.RandomState(9)
    kp, vp = _rand_pool(rng, NB, bt, H, Dh)
    q = jnp.asarray(rng.randn(B, H, Dh).astype(np.float32))
    tables = jnp.asarray(
        np.array([[0, 2, 3, NB], [1, 4, NB, NB], [5, NB, NB, NB]],
                 np.int32))
    positions = jnp.asarray(np.array([25, 10, 7], np.int32))
    exact = pa.paged_attention_reference(q, kp, vp, tables, positions)
    for kvd in pa.KV_DTYPES:
        if kvd == "native":
            continue
        kq, ks = pa.quantize_kv(kp, kvd)
        vq, vs = pa.quantize_kv(vp, kvd)
        out = pa.paged_decode_attention(q, kq, vq, tables, positions,
                                        k_scale=ks, v_scale=vs)
        ref = pa.paged_attention_reference(q, kq, vq, tables, positions,
                                           k_scale=ks, v_scale=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5, err_msg=kvd)
        err = float(jnp.max(jnp.abs(out - exact)))
        assert err < 0.08, (kvd, err)  # ~1% of unit-variance outputs


def test_quantize_roundtrip_and_bytes_accounting():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(5, 4, 16).astype(np.float32) * 3.0)
    q, s = pa.quantize_kv(x, "int8")
    assert q.dtype == jnp.int8 and s.shape == (5, 4)
    back = pa.dequantize_kv(q, s)
    # absmax/127 symmetric quantization: elementwise error <= scale/2
    # from rounding + up to 127 * 2^-11 * scale from the f16-stored
    # scale's own rounding (~0.56 total).
    assert float(jnp.max(jnp.abs(back - x)
                         / jnp.maximum(s.astype(jnp.float32)[..., None],
                                       1e-8))) <= 0.57
    # Zero rows survive (scale floors at eps instead of dividing by 0).
    qz, sz = pa.quantize_kv(jnp.zeros((2, 2, 8)), "int8")
    assert float(jnp.max(jnp.abs(pa.dequantize_kv(qz, sz)))) == 0.0
    # bytes-per-token: int8 payload + one f16 scale vs 2-byte bf16.
    assert pa.kv_bytes_per_token("int8", 64, jnp.bfloat16) == 64 + 2
    assert pa.kv_bytes_per_token("native", 64, jnp.bfloat16) == 128
    assert pa.kv_bytes_per_token("native", 64, jnp.float32) == 256


# -- the clip-mode hole hazard ------------------------------------------------

def _poison_last_block(eng):
    """Write extreme finite garbage into pool block NB-1 — the block the
    hole sentinel CLAMPS onto.  Finite (not NaN) on purpose: the
    contract is contribution-masking (clamped entries get softmax weight
    exactly 0), and 0 * NaN would poison even a correct mask — the
    regression must fail on mask regressions, not on IEEE NaN rules."""
    nb = eng.blocks.capacity
    garbage = 1e30
    cache = dict(eng._cache)
    for key in ("k", "v"):
        arr = cache[key]
        cache[key] = arr.at[:, nb - 1].set(
            jnp.full(arr.shape[1:][1:], garbage, arr.dtype))
    eng._cache = cache
    return nb


@pytest.mark.parametrize("impl", ["gather", "kernel"])
def test_poisoned_pool_block_never_leaks_through_clip_mask(impl):
    """The poisoned-pool regression (ISSUE 8 satellite): garbage in the
    last REAL block — exactly where ``mode="clip"`` clamps every hole
    sentinel — must leave decode outputs unchanged in both impls.  The
    pool is sized so block NB-1 is never allocated (the free list hands
    out low ids first), so every read of it is a clamped hole read."""
    model, params = _tiny()
    prompt = np.random.RandomState(4).randint(0, 61, (2 * BT + 3,)).tolist()
    ref = _flax_greedy(model, params, prompt, 6)
    eng = _engine(params, impl, num_blocks=16).start()
    try:
        assert eng.generate(prompt, max_new_tokens=6) == ref
        nb = _poison_last_block(eng)
        # The poisoned block must still be unallocated (all reads of it
        # are clamped holes) — and stay so through the next request.
        assert eng.blocks.refcount(nb - 1) == 0
        assert eng.generate(prompt, max_new_tokens=6) == ref, \
            "clamped hole reads leaked into the output"
        assert eng.blocks.refcount(nb - 1) == 0
    finally:
        eng.stop()


# -- engine-level kernel-vs-gather parity -------------------------------------

@pytest.mark.slow  # ~30s sweep; batched-equals-single kernel parity stays
def test_kernel_engine_matches_gather_engine_at_block_boundaries():
    """Token-stream parity across prompt lengths straddling block and
    jit-bucket boundaries (k·BT, k·BT±1), chunk budget unaligned with
    BT — and both equal the flax recompute."""
    model, params = _tiny()
    g = _engine(params, "gather").start()
    k = _engine(params, "kernel").start()
    try:
        for plen in (BT - 1, BT, BT + 1, 2 * BT, 2 * BT + 1, 3):
            prompt = np.random.RandomState(plen).randint(
                0, 61, (plen,)).tolist()
            got_g = g.generate(prompt, max_new_tokens=5)
            got_k = k.generate(prompt, max_new_tokens=5)
            assert got_g == got_k, f"plen={plen}"
            assert got_k == _flax_greedy(model, params, prompt, 5), \
                f"plen={plen}"
    finally:
        g.stop()
        k.stop()


def test_kernel_engine_batched_equals_single():
    """The engine exactness contract holds under the kernel impl: a
    concurrent storm == the same prompts served alone, bit-for-bit."""
    _, params = _tiny()
    eng = _engine(params, "kernel", max_batch=8).start()
    try:
        prompts = [np.random.RandomState(i).randint(
            0, 61, (3 + (i * 5) % (2 * BT),)).tolist() for i in range(8)]
        singles = [eng.generate(p, max_new_tokens=5) for p in prompts]
        results = [None] * len(prompts)

        def run(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=5)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == singles
        assert eng.metrics.snapshot()["occupancy"]["max"] > 1
    finally:
        eng.stop()


def test_kernel_engine_poisoned_batch_recovery():
    """Poisoned-batch recovery under HVD_SERVE_ATTN_IMPL=kernel: the
    failed iteration's block refs are freed, the registry survives, and
    the replica keeps answering exactly."""
    model, params = _tiny()

    class _PoisonOnce:
        def __init__(self, inner):
            self._inner = inner
            self.armed = False
            for attr in ("vocab_size", "max_len", "block_tokens",
                         "kv_token_cost", "attn_impl", "kv_dtype"):
                setattr(self, attr, getattr(inner, attr))

        @property
        def max_blocks_per_seq(self):
            return self._inner.max_blocks_per_seq

        def paged_block_bytes(self):
            return self._inner.paged_block_bytes()

        def init_paged_cache(self, num_blocks, max_batch):
            return self._inner.init_paged_cache(num_blocks, max_batch)

        def prefill_chunk(self, cache, chunks, starts, tables):
            return self._inner.prefill_chunk(cache, chunks, starts, tables)

        def decode_paged(self, cache, tokens, positions, tables):
            if self.armed:
                self.armed = False
                raise RuntimeError("simulated device fault")
            return self._inner.decode_paged(cache, tokens, positions,
                                            tables)

        def copy_block(self, cache, src, dst):
            return self._inner.copy_block(cache, src, dst)

    ad = _PoisonOnce(TransformerAdapter(_TINY, params, block_tokens=BT,
                                        attn_impl="kernel"))
    eng = InferenceEngine(ad, kv_mode="paged", max_batch=4,
                          prefill_chunk=64, replica_id="k-poison").start()
    try:
        shared = list(range(2 * BT))
        warm = eng.generate(shared + [3], max_new_tokens=4)
        assert warm == _flax_greedy(model, params, shared + [3], 4)
        ad.armed = True
        doomed = Request(shared + [9], max_new_tokens=8)
        eng.batcher.submit(doomed)
        with pytest.raises(RuntimeError, match="simulated device fault"):
            doomed.result(timeout=30)
        stats = eng.kv_stats()
        assert stats["used"] == 0
        assert stats["retained"] > 0  # registry survived
        assert eng.generate(shared + [3], max_new_tokens=4) == warm
    finally:
        eng.stop()


def test_kernel_engine_pool_exhaustion_preempts_youngest():
    """The defensive preemption path under the kernel impl (hand-built
    over-committed pool, same shape as the gather-path pin)."""
    _, params = _tiny()
    ad = TransformerAdapter(_TINY, params, block_tokens=BT,
                            attn_impl="kernel")
    eng = InferenceEngine(ad, kv_mode="paged", max_batch=4, num_blocks=2,
                          prefill_chunk=64, replica_id="k-exhaust")
    from horovod_tpu.serve.engine import _Seq
    old_req = Request([1] * BT, max_new_tokens=4)
    old_req.generated = [5]
    young_req = Request([2] * BT, max_new_tokens=4)
    young_req.generated = [7]
    old = _Seq(old_req, 0, eng.blocks.allocate(2), [], admit_seq=0)
    old.length = BT
    old.prompt_pos = BT
    young = _Seq(young_req, 0, [], [], admit_seq=1)
    young.length = BT
    young.prompt_pos = BT
    eng._slots[0] = old
    eng._slots[1] = young
    eng._decode_once_paged()
    assert eng._slots[1] is None
    assert young_req.generated == [] and young_req.requeues == 1
    assert eng.metrics.snapshot()["requests"]["preempted"] == 1
    assert len(old_req.generated) == 2


# -- quantized KV through the engine ------------------------------------------

@pytest.mark.slow  # ~18s
def test_int8_engine_error_bounds_and_batched_equals_single():
    """int8 KV blocks: batched==single inside the int8 engine (the
    exactness contract at any storage dtype), and final logits within
    pinned cosine/abs tolerance of bf16 storage."""
    _, params = _tiny()
    ad8 = TransformerAdapter(_TINY, params, block_tokens=BT,
                             kv_dtype="int8")
    ad16 = TransformerAdapter(_TINY, params, block_tokens=BT,
                              kv_dtype="bf16")
    prompts = [np.random.RandomState(i).randint(
        0, 61, (5 + 3 * i,)).tolist() for i in range(4)]
    for p in prompts:
        l8 = ad8.prompt_logits(p)
        l16 = ad16.prompt_logits(p)
        cos = float(np.dot(l8, l16)
                    / (np.linalg.norm(l8) * np.linalg.norm(l16)))
        assert cos > 0.999, cos
        assert float(np.max(np.abs(l8 - l16))) < 0.05
    eng = InferenceEngine(ad8, kv_mode="paged", max_batch=4,
                          prefill_chunk=5, replica_id="int8").start()
    try:
        singles = [eng.generate(p, max_new_tokens=5) for p in prompts]
        results = [None] * len(prompts)

        def run(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=5)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == singles
    finally:
        eng.stop()


@pytest.mark.slow  # ~10s dtype sweep
def test_prefix_cache_hashing_unaffected_by_storage_dtype():
    """Prefix hashes are token-content based, so int8 storage reuses
    cached blocks exactly like bf16 — same hit tokens, identical output
    (a cached quantized block holds the same ints a re-prefill would
    write)."""
    _, params = _tiny()
    shared = np.random.RandomState(7).randint(0, 61, (2 * BT,)).tolist()
    hits = {}
    outs = {}
    for kvd in ("native", "int8"):
        eng = _engine(params, "gather", kv_dtype=kvd,
                      prefill_chunk=64).start()
        try:
            a = eng.generate(shared + [5], max_new_tokens=4)
            b = eng.generate(shared + [5], max_new_tokens=4)
            assert a == b  # cached-prefix decode == cold decode
            hits[kvd] = eng.kv_stats()["prefix_hit_tokens"]
            outs[kvd] = a
        finally:
            eng.stop()
    assert hits["native"] == hits["int8"] > 0
    # int8's token stream may differ from native's (logits shifted), but
    # on this prompt the argmax margin dominates the quantization noise:
    assert outs["native"] == outs["int8"]


def test_paged_block_bytes_matches_pool_and_manager():
    _, params = _tiny()
    # _TINY head_dim = 16: f32 native 64 B, bf16 32 B, int8 16+2 B per
    # (token, head) of K or V.
    for kvd, per_tok_head in (("native", 16 * 4), ("bf16", 16 * 2),
                              ("int8", 16 + 2)):
        ad = TransformerAdapter(_TINY, params, block_tokens=BT,
                                kv_dtype=kvd)
        expect = _TINY.num_layers * 2 * BT * _TINY.num_heads * per_tok_head
        assert ad.paged_block_bytes() == expect, kvd
        eng = InferenceEngine(ad, kv_mode="paged", max_batch=2,
                              num_blocks=4, replica_id=f"bytes-{kvd}")
        stats = eng.kv_stats()
        assert stats["bytes_per_block"] == expect
        assert stats["kv_bytes_per_token"] == expect / BT
        assert stats["bytes_total"] == 4 * expect
        assert stats["kv_dtype"] == kvd
        # The device pool really is smaller under int8: sum of leaf
        # bytes tracks the accounting (scale rows included).
        pool = ad.init_paged_cache(4, 2)
        nbytes = sum(a.size * a.dtype.itemsize for a in pool.values())
        assert nbytes == 4 * expect, kvd


def test_fp8_engine_generates_when_supported():
    if "fp8" not in pa.KV_DTYPES:
        pytest.skip("no float8_e4m3fn in this jax build")
    model, params = _tiny()
    prompt = [3, 17, 42, 9, 11]
    eng = _engine(params, "gather", kv_dtype="fp8").start()
    try:
        out = eng.generate(prompt, max_new_tokens=4)
        assert out == _flax_greedy(model, params, prompt, 4)
    finally:
        eng.stop()


def test_knob_validation_errors():
    _, params = _tiny()
    with pytest.raises(ValueError, match="attn_impl"):
        TransformerAdapter(_TINY, params, attn_impl="fused")
    with pytest.raises(ValueError, match="kv_dtype"):
        TransformerAdapter(_TINY, params, kv_dtype="int4")
    with pytest.raises(ValueError, match="outside"):
        TransformerAdapter(_TINY, params).prompt_logits([])


def test_env_knob_resolution(monkeypatch):
    _, params = _tiny()
    monkeypatch.setenv("HVD_SERVE_ATTN_IMPL", "kernel")
    monkeypatch.setenv("HVD_SERVE_KV_DTYPE", "int8")
    ad = TransformerAdapter(_TINY, params, block_tokens=BT)
    assert ad.attn_impl == "kernel" and ad.kv_dtype == "int8"
    monkeypatch.setenv("HVD_SERVE_ATTN_IMPL", "auto")
    ad = TransformerAdapter(_TINY, params, block_tokens=BT)
    # auto = kernel on TPU, gather elsewhere (this suite runs on CPU).
    assert ad.attn_impl == "gather"


# -- export surfaces ----------------------------------------------------------

def test_metrics_expose_kv_bytes_impl_and_dtype_gauges():
    _, params = _tiny()
    eng = _engine(params, "kernel", kv_dtype="int8").start()
    eng.metrics.register_kv_stats("pa-kernel", eng.kv_stats)
    try:
        eng.generate([1, 2, 3], max_new_tokens=3)
        snap = eng.metrics.snapshot()
        s = snap["kv_blocks"]["pa-kernel"]
        assert s["attn_impl"] == "kernel"
        assert s["kv_dtype"] == "int8"
        assert s["kv_bytes_per_token"] > 0
        text = eng.metrics.render()
        assert 'hvd_serve_kv_bytes_per_token{replica="pa-kernel"}' in text
        assert ('hvd_serve_attention_impl{replica="pa-kernel",'
                'impl="kernel"} 1') in text
        assert ('hvd_serve_kv_dtype{replica="pa-kernel",'
                'dtype="int8"} 1') in text
    finally:
        eng.stop()


def test_replica_to_dict_carries_impl_and_dtype():
    from horovod_tpu.serve import Replica
    _, params = _tiny()
    eng = _engine(params, "kernel", kv_dtype="int8")
    d = Replica("r0", None, eng).to_dict()
    assert d["attn_impl"] == "kernel"
    assert d["kv_dtype"] == "int8"
    assert d["kv_blocks"]["bytes_per_block"] == \
        eng.adapter.paged_block_bytes()


def test_slot_mode_reports_what_it_runs_not_adapter_config():
    """Review finding: slot mode ignores attn_impl/kv_dtype (dense
    attention over the compute-dtype slot cache), so its export
    surfaces must say so instead of echoing knobs it never applies."""
    from horovod_tpu.serve import Replica
    _, params = _tiny()
    ad = TransformerAdapter(_TINY, params, block_tokens=BT,
                            attn_impl="kernel", kv_dtype="int8")
    eng = InferenceEngine(ad, kv_mode="slot", max_batch=2,
                          replica_id="slot-r")
    assert eng.attn_impl == "dense"
    assert eng.kv_dtype == "native"
    d = Replica("slot-r", None, eng).to_dict()
    assert d["attn_impl"] == "dense" and d["kv_dtype"] == "native"
