"""Adversarial scale-out of subset collectives: 64-256 virtual devices,
odd-size process sets, and the documented memory ceiling of the
subset-allgather transient (docs/process_sets.md "TPU lowering" table;
reference semantics process_set.h:26).

The 8-device conftest mesh cannot express these worlds, so each case runs
in a subprocess with its own ``xla_force_host_platform_device_count``.
256 devices on this one-core host compiles but crawls; 64 and 128 run in
the default suite and 256 behind HVD_TPU_HEAVY_TESTS=1.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Serialize with the other subprocess-world e2e files (conftest
# pytest_collection_modifyitems): overlapping multi-process worlds on one
# host core cascade spurious stall timeouts.
pytestmark = pytest.mark.xdist_group("heavy_e2e")

SCRIPT = r"""
import json, os, sys
N = int(os.environ["PSS_DEVICES"])
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={N}"
os.environ["HVD_TPU_EMULATE_RANKS"] = str(N)
sys.path.insert(0, "__REPO__")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops as C

hvd.init()
mesh = hvd.mesh()

def run(body, *stacked, out_specs=None):
    def inner(*xs):
        outs = body(*(x[0] for x in xs))
        outs = outs if isinstance(outs, tuple) else (outs,)
        return tuple(o[None] for o in outs)
    res = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=tuple(P("hvd") for _ in stacked),
        out_specs=out_specs or P("hvd")))(*stacked)
    return res if len(res) > 1 else res[0]

rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(N, 6).astype(np.float32))

# Odd-size sets: a 5-member scattered set and a prime-size prefix set.
scattered = (1, 5, 7, N - 4, N - 1)
prime = tuple(range(37 if N >= 37 else 5))

# 1) subset allreduce: members reduce over the set, non-members keep input.
for members in (scattered, prime):
    out = np.asarray(run(lambda t: C.allreduce(t, C.Sum, members=members), x))
    expect = np.sum(np.asarray(x)[list(members)], axis=0)
    for r in range(N):
        want = expect if r in members else np.asarray(x)[r]
        np.testing.assert_allclose(out[r], want, rtol=1e-5,
                                   err_msg=f"allreduce members={members} r={r}")

# 2) subset PRODUCT (member-ring ppermute, exact)
sub = scattered
outp = np.asarray(run(lambda t: C.allreduce(t, C.Product, members=sub), x))
expectp = np.prod(np.asarray(x)[list(sub)], axis=0)
for r in sub:
    np.testing.assert_allclose(outp[r], expectp, rtol=1e-4)

# 3) member-ring alltoall on an odd-size set: k splits of k blocks.
k = len(sub)
xa = jnp.asarray(rng.randn(N, k * 2).astype(np.float32))
outa = np.asarray(run(lambda t: C.alltoall(t, members=sub), xa))
arr = np.asarray(xa)
for i, r in enumerate(sub):
    expect = np.concatenate([arr[s][i * 2:(i + 1) * 2] for s in sub])
    np.testing.assert_allclose(outa[r], expect, rtol=1e-5,
                               err_msg=f"alltoall member {r}")

# 4) subset allgather: correctness + the documented O(N*|x|) transient
# ceiling — the lowering may gather the FULL axis before selecting the
# k members, but never more (an O(N^2)-style regression must fail here).
ks = len(sub)
outg = run(lambda t: C.allgather(t, members=sub), x,
           out_specs=P("hvd"))
outg = np.asarray(outg)
gather_expect = np.asarray(x)[list(sub)]
for r in sub:
    np.testing.assert_allclose(outg[r].reshape(ks, -1), gather_expect,
                               rtol=1e-5)

def lowered_max_elems():
    def inner(t):
        return C.allgather(t[0], members=sub)[None]
    lowered = jax.jit(jax.shard_map(inner, mesh=mesh, in_specs=(P("hvd"),),
                                    out_specs=P("hvd"))).lower(x)
    txt = lowered.compile().as_text()
    import re
    best = 0
    for m in re.finditer(r"f32\[([0-9,]+)\]", txt):
        elems = 1
        for d in m.group(1).split(","):
            elems *= int(d)
        best = max(best, elems)
    return best

per_shard = x.shape[1]          # |x| per slot
ceiling = N * per_shard         # documented transient bound
max_elems = lowered_max_elems()
assert max_elems <= ceiling, (max_elems, ceiling)

print(json.dumps({"devices": N, "max_transient_elems": max_elems,
                  "ceiling": ceiling, "ok": True}))
"""


def _run_case(n_devices: int, timeout: int = 900):
    env = dict(os.environ, PSS_DEVICES=str(n_devices))
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT.replace("__REPO__", REPO)],
        env=env, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"]
    return out


@pytest.mark.integration
def test_subset_collectives_64_devices():
    out = _run_case(64)
    assert out["max_transient_elems"] <= out["ceiling"]


@pytest.mark.integration
@pytest.mark.slow  # ~6s; 64-device variant stays in tier-1
def test_subset_collectives_128_devices():
    _run_case(128)


@pytest.mark.integration
@pytest.mark.skipif(not os.environ.get("HVD_TPU_HEAVY_TESTS"),
                    reason="256 virtual devices crawls on a 1-core host; "
                           "set HVD_TPU_HEAVY_TESTS=1")
def test_subset_collectives_256_devices():
    _run_case(256, timeout=1800)
