"""bench.py outage fallback: the emit-first contract.

The driver parses the LAST stdout JSON line of ``python bench.py``
(BENCH_r{N}.json).  Four rounds of relay outages produced null records
(BENCH_r01-r04) because the fallback emission raced the driver's kill;
round 5 made the fallback emit-FIRST: the last persisted capture prints
(labeled ``stale: true``) before any device probe, so a kill at ANY point
leaves a parseable record.  These tests pin that contract.

Probe failure is forced deterministically by unsetting
PALLAS_AXON_POOL_IPS: the axon PJRT plugin then never registers and
``jax.devices()`` raises immediately (no dependence on relay state).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "bench.py")
_TAG = "pytestfallback"
_RECORD_PATH = os.path.join(_REPO, "artifacts", f"last_bench_{_TAG}.json")

_FAKE_RECORD = {
    "metric": "resnet50_synthetic_images_per_sec",
    "value": 1234.5,
    "unit": "images/sec",
    "vs_baseline": 11.92,
    "config": "fake record planted by test_bench_fallback",
    "captured_at": "2026-01-01T00:00:00Z",
}


def _bench_env(tag, **overrides):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # plugin never registers...
    env["JAX_PLATFORMS"] = "axon"  # ...and this makes devices() raise
    # (not fall back to CPU) even in a shell without the ambient var
    # Sanitize every record-keying / behavior knob an ambient shell could
    # export — an inherited BENCH_FAST_STEM=0 would silently re-key
    # _last_good_path away from the records these tests plant.
    for var in ("BENCH_MODEL", "BENCH_FAST_STEM", "BENCH_SMOKE",
                "BENCH_PROFILE", "BENCH_BERT_BATCH", "BENCH_BERT_ATTN",
                "BENCH_BERT_MLMPOS", "BENCH_GPT2_BATCH",
                "BENCH_SERVE_REQUESTS", "BENCH_SERVE_NEWTOKENS",
                "BENCH_SERVE_REPLICAS", "BENCH_SERVE_SLOT_BATCH",
                "HVD_SERVE_BLOCK_TOKENS", "HVD_SERVE_PREFILL_CHUNK",
                "HVD_SERVE_PREFIX_CACHE", "HVD_SERVE_KV_MODE",
                "HVD_SERVE_ATTN_IMPL", "HVD_SERVE_KV_DTYPE",
                "HVD_SERVE_NUM_BLOCKS", "HVD_SERVE_MAX_BATCH",
                "HVD_SERVE_SPEC_K", "HVD_SERVE_DRAFT_LAYERS",
                "BENCH_SERVE_SPEC_K", "BENCH_SERVE_SAMPLE_TEMP",
                "BENCH_SERVE_SLO_MS", "HVD_SERVE_CTL_ENABLE",
                "HVD_SERVE_CTL_SLO_MS", "HVD_SERVE_CTL_MAX_REPLICAS",
                "HVD_SERVE_CTL_POLL_S", "HVD_SERVE_CTL_MIN_REPLICAS",
                "HVD_SERVE_CTL_QUEUE_HIGH", "HVD_SERVE_CTL_QUEUE_LOW",
                "HVD_SERVE_CTL_BROWNOUT_MAX_NEW",
                "HVD_SERVE_QOS_LAT_QUEUE", "HVD_SERVE_QOS_TPT_QUEUE",
                "HVD_SERVE_RETRY_AFTER_CAP_S",
                "HVD_SERVE_TENANT_WEIGHTS", "HVD_SERVE_TENANT_QUEUE",
                "HVD_SERVE_TENANT_TOKENS", "HVD_SERVE_TENANT_QUANTUM",
                "HVD_SERVE_TENANT_MAX_LABELS",
                "HVD_SERVE_COMPILE_CACHE", "HVD_SERVE_WARMUP",
                "HVD_SERVE_TIER", "HVD_SERVE_TIER_KV",
                "HVD_SERVE_TIER_HOST_BLOCKS",
                "HVD_SERVE_TIER_DEMOTE_ITERS", "HVD_SERVE_TIER_PREFETCH",
                "HVD_SERVE_TIER_OVERSUB", "HVD_SERVE_TIER_QUANTUM",
                "HVD_SERVE_TIER_FETCH_TIMEOUT_S",
                "HVD_SERVE_TIER_PUBLISH",
                "HVD_SERVE_SP", "HVD_SERVE_SP_MIN_TOKENS",
                "BENCH_SERVE_SP_RANKS",
                "HVD_SERVE_DRAIN_S", "HVD_ROUTE_AFFINITY_BLOCKS",
                "HVD_ROUTE_VNODES", "HVD_ROUTE_BOUNDED_LOAD",
                "HVD_ROUTE_HEDGE_MS", "HVD_ROUTE_RETRY_MAX",
                "HVD_ROUTE_RETRY_BASE_MS", "HVD_ROUTE_RETRY_CAP_MS",
                "HVD_ROUTE_EJECT_FAILURES", "HVD_ROUTE_PROBE_S",
                "HVD_ROUTE_HEALTH_S", "HVD_ROUTE_CONNECT_TIMEOUT_S",
                "HVD_ROUTE_DEFAULT_TIMEOUT_S", "HVD_ROUTE_DRAIN_S",
                "HVD_ROUTE_ENDPOINTS", "HVD_ROUTE_PORT",
                "HVD_FAULTLINE_SEED", "HVD_FAULTLINE_PLAN",
                "HVD_KV_RETRY_MAX", "HVD_KV_RETRY_BASE_MS",
                "HVD_KV_RETRY_CAP_MS", "HVD_SANITIZE", "HVD_RACE_RAISE",
                "HVD_TRACE_SAMPLE", "HVD_TRACE_DIR", "HVD_TRACE_RECENT",
                "HVD_TIMELINE_QUEUE_CAP", "HVD_ANALYZE",
                "HVD_MEM_BUDGET_BYTES", "HVD_MEM_UPCAST_MIN_BYTES",
                "HVD_COMM_BUDGET_BYTES", "HVD_COMM_DCN_BUDGET_BYTES",
                "HVD_COMM_DCN_AXES"):
        env.pop(var, None)
    env["HVD_TPU_BENCH_TAG"] = tag
    env["BENCH_PROBE_BUDGET_S"] = "3"
    env["BENCH_PROBE_TIMEOUT_S"] = "5"
    env.update(overrides)
    return env


@pytest.fixture()
def planted_record():
    os.makedirs(os.path.dirname(_RECORD_PATH), exist_ok=True)
    with open(_RECORD_PATH, "w") as f:
        json.dump(_FAKE_RECORD, f)
    yield _FAKE_RECORD
    try:
        os.remove(_RECORD_PATH)
    except OSError:
        pass


def _json_lines(text):
    return [json.loads(l) for l in text.splitlines()
            if l.strip().startswith("{")]


def test_stale_record_emitted_before_probe(planted_record):
    r = subprocess.run([sys.executable, _BENCH], env=_bench_env(_TAG),
                       capture_output=True, text=True, timeout=120)
    assert r.returncode != 0  # probe failed; no fresh capture
    records = _json_lines(r.stdout)
    assert records, f"no JSON line on stdout: {r.stdout!r} / {r.stderr!r}"
    last = records[-1]
    assert last["stale"] is True
    # Stale provenance is top-level and in-band (the BENCH_r05 stale
    # re-emission confusion): a re-emitted record names its source round
    # (capture_round counter; captured_at for pre-counter records).
    assert last["stale_source_round"] == planted_record["captured_at"]
    assert last["value"] == planted_record["value"]
    assert "process start" in last["stale_reason"]
    assert "no usable accelerator" in r.stderr


def test_sigkill_at_any_point_leaves_parseable_record(planted_record,
                                                      tmp_path):
    """The record must be on stdout (flushed) before probing even starts,
    so a driver kill mid-probe cannot produce a null BENCH record."""
    out = open(tmp_path / "stdout.txt", "w+")
    p = subprocess.Popen([sys.executable, _BENCH], env=_bench_env(_TAG),
                         stdout=out, stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            out.flush()
            if os.path.getsize(out.name) > 0:
                break
            time.sleep(0.1)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        p.kill()
        out.close()
    records = _json_lines(open(out.name).read())
    assert records and records[-1]["stale"] is True
    assert records[-1]["value"] == _FAKE_RECORD["value"]


def test_probe_deadline_emits_fail_fast_record(planted_record):
    """ISSUE 1 satellite: the probe loop must give up at its own deadline
    (default well inside the driver's ~870 s window — BENCH_r05 showed the
    unbounded loop riding to rc=124) and re-emit the fallback as a
    fail-fast JSON line carrying the probe-failure metadata in-band."""
    r = subprocess.run([sys.executable, _BENCH], env=_bench_env(_TAG),
                       capture_output=True, text=True, timeout=120)
    assert r.returncode != 0  # never confusable with a fresh capture
    records = _json_lines(r.stdout)
    assert len(records) >= 2  # emit-first floor + fail-fast re-emission
    last = records[-1]
    assert last["stale"] is True
    assert last["stale_source_round"] == planted_record["captured_at"]
    assert last["probe_failed"] is True
    assert last["probe_attempts"] >= 1
    assert last["probe_seconds"] >= 0
    assert last["value"] == planted_record["value"]
    assert "fail-fast" in r.stderr
    # The on-disk capture stays clean — probe failure is never persisted.
    with open(_RECORD_PATH) as f:
        assert "probe_failed" not in json.load(f)


def test_no_prior_capture_fails_with_clear_message():
    r = subprocess.run([sys.executable, _BENCH],
                       env=_bench_env("nosuchtagever"),
                       capture_output=True, text=True, timeout=120)
    assert r.returncode != 0
    assert not _json_lines(r.stdout)  # nothing to emit — and says so
    assert "no prior capture" in r.stderr


def test_serve_bench_smoke_emits_throughput_and_latency(tmp_path):
    """ISSUE 4 satellite + ISSUE 5 satellite: BENCH_MODEL=serve runs the
    continuous-batching serving microbench (bench.bench_serve)
    end-to-end on CPU under BENCH_SMOKE shapes and the emitted record
    carries the throughput AND latency keys the serving story is judged
    on — tokens/sec, the TTFT / per-output-token split, achieved batch
    occupancy — plus the ISSUE 5 paged/chunked/prefix arm records with
    their config keys and in-band exactness checks."""
    tag = "pytestservesmoke"
    path = os.path.join(_REPO, "artifacts",
                        f"last_bench_serve_smoke_{tag}.json")
    env = _bench_env(tag, JAX_PLATFORMS="cpu", BENCH_MODEL="serve",
                     BENCH_SMOKE="1", BENCH_PROBE_BUDGET_S="60",
                     BENCH_PROBE_TIMEOUT_S="30")
    try:
        r = subprocess.run([sys.executable, _BENCH], env=env,
                           capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        records = _json_lines(r.stdout)
        assert records, r.stdout
        last = records[-1]
        assert last["metric"] == "serve_tokens_per_sec"
        assert last["unit"] == "tokens/sec"
        assert last["value"] > 0
        for key in ("ttft_p50_ms", "ttft_p99_ms", "token_step_p50_ms",
                    "token_step_p99_ms", "occupancy_mean",
                    "occupancy_max"):
            assert key in last, f"{key} missing from serve record: {last}"
        # Continuous batching demonstrably engaged even in the smoke run.
        assert last["occupancy_max"] > 1
        assert last["requests"]["ok"] >= 16
        # ISSUE 5: the paged-cache config keys and the three arms.
        assert last["kv_mode"] == "paged"
        assert last["block_tokens"] == 16
        assert last["prefill_chunk"] > 0
        assert last["prefix_cache"] is True
        paged = last["paged"]
        for key in ("budget_tokens", "admitted_concurrent",
                    "slot_admitted_concurrent", "admit_ratio",
                    "tokens_per_sec", "slot_tokens_per_sec"):
            assert key in paged, f"paged.{key} missing: {paged}"
        assert paged["outputs_match"] is True  # batched==single==slot
        chunked = last["chunked"]
        for key in ("prefill_chunk", "token_step_p99_ms",
                    "unchunked_token_step_p99_ms"):
            assert key in chunked, f"chunked.{key} missing: {chunked}"
        assert chunked["outputs_match"] is True
        # ISSUE 20: the SP variant of the interference storm keeps the
        # chunked-prefill contract — SP prefill never worsens decode
        # tail vs the unchunked baseline — and stays bit-exact.
        for key in ("sp_token_step_p99_ms", "sp_p99_bounded",
                    "sp_outputs_match"):
            assert key in chunked, f"chunked.{key} missing: {chunked}"
        assert chunked["sp_outputs_match"] is True
        assert chunked["sp_p99_bounded"] is True
        # ISSUE 20: the sequence-parallel prefill arm — emulated
        # multi-rank long-prompt prefill with token-exact outputs, the
        # emulation-model speedup, and the handoff/ring accounting.
        sp = last["sp_prefill"]
        for key in ("ranks", "emulated", "jobs", "speedup",
                    "baseline_prefill_p50_ms", "sp_prefill_wall_p50_ms",
                    "baseline_ttft_p50_ms", "ttft_p50_ms",
                    "handoff_bytes", "ring_hops",
                    "ring_bytes_per_prefill", "outputs_match"):
            assert key in sp, f"sp_prefill.{key} missing: {sp}"
        assert sp["outputs_match"] is True  # SP ≡ single-rank, exact
        assert sp["emulated"] is True       # CPU-hermetic emulation
        assert sp["jobs"] >= 1              # the SP path really engaged
        assert sp["handoff_bytes"] > 0
        assert sp["ring_hops"] > 0
        prefix = last["prefix"]
        for key in ("enabled", "hit_rate", "hit_tokens", "cow_copies"):
            assert key in prefix, f"prefix.{key} missing: {prefix}"
        assert prefix["hit_rate"] > 0  # shared-prefix storm really hit
        # ISSUE 8: attention impl + KV storage dtype are visible in the
        # record, and the two new arms carry their keys with in-band
        # exactness.  The kernel arm runs under the Pallas interpreter
        # on CPU (recorded), so the hermetic bench keeps tracking the
        # kernel's trend while on-chip capture is unavailable.
        assert last["attn_impl"] in ("gather", "kernel")
        assert last["kv_dtype"] == "native"
        kernel = last["kernel"]
        for key in ("interpret", "outputs_match", "tokens_per_sec",
                    "gather_tokens_per_sec", "token_step_p50_ms",
                    "token_step_p99_ms", "gather_token_step_p50_ms",
                    "gather_token_step_p99_ms"):
            assert key in kernel, f"kernel.{key} missing: {kernel}"
        assert kernel["outputs_match"] is True  # kernel == gather, exact
        assert kernel["interpret"] is True      # CPU-hermetic run
        kvarm = last["kv_dtype_arm"]
        for key in ("budget_bytes", "bytes_per_block_bf16",
                    "bytes_per_block_int8", "admit_ratio",
                    "max_logit_err", "outputs_match"):
            assert key in kvarm, f"kv_dtype_arm.{key} missing: {kvarm}"
        # The fixed-HBM-budget acceptance bar: int8 blocks admit >= 1.8x
        # the concurrent sequences bf16 blocks do, exactness (batched ==
        # single within the int8 engine) intact, logit error bounded.
        assert kvarm["admit_ratio"] >= 1.8
        assert kvarm["outputs_match"] is True
        assert 0 <= kvarm["max_logit_err"] < 0.5
        # ISSUE 6: the fault arm — the bench trajectory records
        # robustness (recovery time + goodput under a seeded plan), not
        # just throughput.
        faults = last["faults"]
        for key in ("seed", "fired", "recovery_s", "goodput_ratio",
                    "requeued", "replica_events"):
            assert key in faults, f"faults.{key} missing: {faults}"
        assert faults["recovery_s"] >= 0   # kill→re-admit→answering
        assert 0 < faults["goodput_ratio"] <= 1
        assert faults["fired"], "the seeded plan never fired"
        assert faults["replica_events"]["mark_alive"] >= 1  # scale-up
        assert faults["outputs_match"] is True  # faults never corrupt
        # ISSUE 9: the trace arm records the sampling-overhead contract
        # in-band — tokens/s with the tracer absent (sample=0, the
        # zero-overhead fast path) vs installed at sample=1 with shard
        # files written, exactness intact either way.
        trace = last["trace"]
        for key in ("sample0_tokens_per_sec", "sample1_tokens_per_sec",
                    "sampled_throughput_ratio", "outputs_match",
                    "spans", "shards"):
            assert key in trace, f"trace.{key} missing: {trace}"
        assert trace["sample0_tokens_per_sec"] > 0
        assert trace["sample1_tokens_per_sec"] > 0
        assert trace["outputs_match"] is True  # tracing never corrupts
        assert trace["spans"] > 0 and trace["shards"] >= 1
        # ISSUE 11: the spec arm — greedy speculation is bit-exact and
        # amortizes the target model (acceptance bar: <= 0.67 target
        # decode invocations per emitted token at k=4, i.e. >= 1.5x).
        spec = last["spec"]
        for key in ("spec_k", "draft_layers", "outputs_match",
                    "acceptance_rate", "drafted", "accepted",
                    "target_calls_per_token", "tokens_per_sec",
                    "baseline_tokens_per_sec"):
            assert key in spec, f"spec.{key} missing: {spec}"
        assert spec["spec_k"] == 4
        assert spec["outputs_match"] is True  # spec-greedy ≡ greedy
        assert spec["drafted"] > 0
        assert spec["target_calls_per_token"] <= 0.67
        # ISSUE 11: the sampling arm — seeded storm determinism and the
        # CoW n-best footprint (n=4 peak pool strictly < 4x the n=1
        # footprint: prompt blocks shared through CoW tables).
        sam = last["sampling"]
        for key in ("temperature", "deterministic", "cow_forks",
                    "forked_requests", "n1_peak_pool_bytes",
                    "n4_peak_pool_bytes", "pool_share_ratio"):
            assert key in sam, f"sampling.{key} missing: {sam}"
        assert sam["deterministic"] is True  # same seeds → same outputs
        assert sam["cow_forks"] == 3 and sam["forked_requests"] == 1
        assert sam["pool_share_ratio"] < 1.0
        assert sam["n4_peak_pool_bytes"] < 4 * sam["n1_peak_pool_bytes"]
        # ISSUE 13: the autoscale arm — a seeded diurnal sweep under the
        # fleet controller scales up and back down, holds the latency
        # SLO, and browning out never changes latency-tier outputs.
        auto = last["autoscale"]
        for key in ("slo_ms", "slo_held", "latency_p99_ms",
                    "scale_events", "brownout_seconds",
                    "max_brownout_level", "shed_throughput",
                    "outputs_match"):
            assert key in auto, f"autoscale.{key} missing: {auto}"
        assert auto["outputs_match"] is True  # brownout ≠ wrong tokens
        assert auto["slo_held"] is True
        assert auto["scale_events"]["scale_up"] >= 1
        assert auto["scale_events"]["scale_down"] >= 1
        assert auto["brownout_seconds"] >= 0.0
        # ISSUE 15: the multitenant arm — two variants on a shared
        # fleet under weighted fair scheduling, a mid-traffic rolling
        # hot-swap with zero failed requests and post-roll exactness,
        # and the warmed cold-start probe.  fair_share_ratio values are
        # recorded for the trend (tiny smoke storms are too short to
        # gate on); the exactness/zero-failure booleans are hard.
        mt = last["multitenant"]
        for key in ("replicas", "tenants", "fair_share_ratio",
                    "swap_zero_failures", "swap_progress",
                    "post_roll_exact", "cold_start_ms", "warmup_runs",
                    "first_request_ms", "tenant_requests"):
            assert key in mt, f"multitenant.{key} missing: {mt}"
        assert mt["swap_zero_failures"] is True
        assert mt["post_roll_exact"] is True
        assert set(mt["fair_share_ratio"]) == {"gold", "silver",
                                               "bronze"}
        prog = mt["swap_progress"]["tuned"]
        assert prog["done"] == prog["total"] >= 1
        assert mt["cold_start_ms"] > 0     # revived replica re-warmed
        assert mt["warmup_runs"] >= 2      # start + the revival re-run
        assert mt["first_request_ms"] > 0
        for t in ("gold", "silver", "bronze"):
            assert mt["tenant_requests"][t]["ok"] >= 1
        # ISSUE 16: the tiered arm — a fixed HBM budget stormed with
        # long-decode requests keeps >= 2x the untiered concurrency by
        # swapping host-ward instead of preempting (zero preemptions,
        # bit-identical outputs), and the migration storm serves a cold
        # replica's shared prefix from a peer's published blocks at
        # least as well as the single-replica prefix arm did locally.
        tiered = last["tiered"]
        for key in ("pool_blocks", "admitted_concurrent",
                    "untiered_admitted_concurrent", "admit_ratio",
                    "outputs_match", "preempted", "swapped_out_seqs",
                    "tier_fault_stall_p50_ms", "tier_fault_stall_p99_ms",
                    "migrated_tokens", "migrated_hit_tokens",
                    "migration_failures", "migration_outputs_match"):
            assert key in tiered, f"tiered.{key} missing: {tiered}"
        assert tiered["admit_ratio"] >= 2.0
        assert tiered["outputs_match"] is True
        assert tiered["preempted"] == 0
        assert tiered["swapped_out_seqs"] >= 1
        assert tiered["migration_outputs_match"] is True
        assert tiered["migration_failures"] == 0
        assert tiered["migrated_tokens"] > 0
        assert tiered["migrated_hit_tokens"] >= last["prefix"]["hit_tokens"]
        # ISSUE 18: the router arm — the hvdroute front door in front of
        # a 2-endpoint fleet keeps the zero-lost contract (every routed
        # response bit-identical to the single-engine reference), keeps
        # prefix affinity, and the hedged sub-arm's tail beats the
        # seeded slow-route train it raced.
        route = last["router"]
        for key in ("endpoints", "requests", "zero_lost",
                    "affinity_hit_rate", "retries", "ejections",
                    "hedges", "hedges_won", "unhedged_p99_ms",
                    "hedged_p99_ms", "hedge_win"):
            assert key in route, f"router.{key} missing: {route}"
        assert route["zero_lost"] is True  # routed ≡ reference, exact
        assert route["endpoints"] >= 2
        assert route["requests"] >= 8
        assert 0 <= route["affinity_hit_rate"] <= 1
        assert route["hedges"] >= 1        # the hedge arm really raced
        assert route["hedge_win"] is True  # hedged p99 <= unhedged p99
        # ISSUE 19: the stream arm — SSE streaming of the same prompts
        # is bit-exact vs buffered, the client-perceived first token
        # beats the buffered full-response wait, a mid-stream hangup
        # frees every KV block, and grammar-constrained sampled
        # completions are 100% schema-valid.
        stream = last["stream"]
        for key in ("sessions", "outputs_match", "buffered_p50_ms",
                    "ttft_p50_ms", "ttft_p99_ms", "intertoken_p99_ms",
                    "ttft_win", "client_gone_kv_used",
                    "client_gone_counted", "schema_valid",
                    "schema_total", "schema_valid_rate"):
            assert key in stream, f"stream.{key} missing: {stream}"
        assert stream["outputs_match"] is True  # streamed ≡ buffered
        assert stream["ttft_win"] is True       # first token ≪ full wait
        assert stream["client_gone_kv_used"] == 0  # hangup freed blocks
        assert stream["client_gone_counted"] >= 1
        assert stream["schema_valid_rate"] == 1.0
        with open(path) as f:  # persisted under the serve+smoke keying
            assert json.load(f)["metric"] == "serve_tokens_per_sec"
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


@pytest.mark.slow  # ~67s: real train capture; smoke test covers tier-1
def test_fresh_capture_supersedes_stale(tmp_path):
    """The SUCCESS path, end-to-end on CPU (BENCH_SMOKE shapes): the
    emit-first stale line prints first, the probe succeeds, a real train
    runs, and the fresh capture is the LAST stdout JSON line and the
    persisted record — the driver's happy path, which otherwise only
    ever executes on the real chip."""
    tag = "pytestsmoke"
    path = os.path.join(_REPO, "artifacts", f"last_bench_smoke_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(dict(_FAKE_RECORD, value=99.9), f)
    env = _bench_env(tag, JAX_PLATFORMS="cpu", BENCH_SMOKE="1",
                     BENCH_PROBE_BUDGET_S="60",
                     BENCH_PROBE_TIMEOUT_S="30",
                     HVD_ANALYZE="1")
    try:
        r = subprocess.run([sys.executable, _BENCH], env=env,
                           capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, r.stderr[-1500:]
        records = _json_lines(r.stdout)
        assert records[0].get("stale") is True     # emit-first floor
        assert records[0]["stale_source_round"]    # provenance in-band
        assert records[0]["value"] == 99.9
        last = records[-1]
        assert "stale" not in last                 # superseded by fresh
        assert last["capture_round"] >= 1          # round counter stamped
        assert last["metric"] == "resnet50_synthetic_images_per_sec"
        assert "SMOKE" in last["config"]
        # HVD_ANALYZE=1 rode along: the shard_step hook checked the step
        # program on first compile and bench surfaced its collective
        # census (count + payload bytes per primitive) in the record.
        census = last["collective_census"]
        assert census["psum"]["count"] >= 1
        assert census["psum"]["bytes"] > 0
        assert last["analysis_findings"] == 0
        # ... and the hvdmem liveness walk rode the same trace: the
        # step's peak live footprint + allocation breakdown land under
        # memory_census (analysis/memplan.py).
        mem = last["memory_census"]
        assert mem["peak_live_bytes"] > 0
        assert mem["input_bytes"] > 0
        assert mem["by_primitive"]
        # ... and the hvdshard sharding walk (analysis/shardplan.py)
        # rode the same trace too: wire bytes per collective + per mesh
        # axis land under comm_census.
        comm = last["comm_census"]
        assert comm["by_primitive"]["psum"]["wire_bytes"] > 0
        assert comm["total_wire_bytes"] > 0
        assert comm["axes_declared"]
        with open(path) as f:
            persisted = json.load(f)
        assert persisted["value"] == last["value"]  # persisted for next time
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
