"""Model zoo tests: shapes, training steps, sequence-parallel equivalence."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import (
    TransformerConfig, Transformer, create_bert, create_gpt2, lm_loss,
    create_resnet50)

N = 8

TINY = TransformerConfig(vocab_size=128, num_layers=2, num_heads=8,
                         d_model=64, d_ff=128, max_len=64, causal=True,
                         dtype=jnp.float32)


@pytest.mark.slow  # ~9s: full resnet50 build; fused-bn test keeps resnet in tier-1
def test_resnet50_forward_shape(hvd8):
    model = create_resnet50(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_gpt_forward_and_loss(hvd8):
    model = Transformer(TINY)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32
    loss = lm_loss(logits[:, :-1], tokens[:, 1:])
    assert float(loss) > 0


def test_gpt_causality(hvd8):
    """Changing a future token must not affect past logits."""
    model = Transformer(TINY)
    rng = np.random.RandomState(1)
    t1 = rng.randint(0, 128, (1, 16))
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 1) % 128  # perturb only the last token
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
    l1 = model.apply(params, jnp.asarray(t1))
    l2 = model.apply(params, jnp.asarray(t2))
    np.testing.assert_allclose(np.asarray(l1[:, :-1]),
                               np.asarray(l2[:, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[:, -1]), np.asarray(l2[:, -1]))


def test_bert_bidirectional(hvd8):
    cfg = dataclasses.replace(TINY, causal=False)
    model = Transformer(cfg)
    rng = np.random.RandomState(2)
    t1 = rng.randint(0, 128, (1, 16))
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 1) % 128
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
    l1 = model.apply(params, jnp.asarray(t1))
    l2 = model.apply(params, jnp.asarray(t2))
    # bidirectional: early positions DO see the change
    assert not np.allclose(np.asarray(l1[:, 0]), np.asarray(l2[:, 0]))


def test_factory_configs(hvd8):
    assert create_gpt2("medium").cfg.num_layers == 24
    assert create_gpt2("medium").cfg.d_model == 1024
    assert create_bert("large").cfg.num_layers == 24
    assert not create_bert("large").cfg.causal
    assert create_bert("base").cfg.vocab_size == 30522


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_seq_parallel_transformer_matches_dense(hvd8, mode):
    """Sequence-parallel attention inside the full model must match the
    dense model exactly (same params, sharded sequence)."""
    cfg_dense = TINY
    cfg_sp = dataclasses.replace(TINY, seq_parallel=mode)
    model_d = Transformer(cfg_dense)
    model_s = Transformer(cfg_sp)
    tokens = jnp.asarray(np.random.RandomState(3).randint(0, 128, (2, 64)))
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    dense_logits = model_d.apply(params, tokens)

    mesh = hvd8.mesh()
    positions = jnp.arange(64)[None, :].repeat(2, axis=0)

    def shard_fwd(tokens, positions):
        return model_s.apply(params, tokens, positions=positions)

    sp_logits = jax.jit(jax.shard_map(
        shard_fwd, mesh=mesh,
        in_specs=(P(None, "hvd"), P(None, "hvd")),
        out_specs=P(None, "hvd")))(tokens, positions)
    np.testing.assert_allclose(np.asarray(sp_logits),
                               np.asarray(dense_logits),
                               rtol=2e-3, atol=2e-3)


def test_gpt_train_step_decreases_loss(hvd8):
    model = Transformer(TINY)
    rng = np.random.RandomState(4)
    tokens = jnp.asarray(rng.randint(0, 128, (8, 32)))
    params = model.init(jax.random.PRNGKey(0), tokens[:1])
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
    state = opt.init(params)

    def local_step(params, state, toks):
        def loss_fn(p):
            logits = model.apply(p, toks)
            return lm_loss(logits[:, :-1], toks[:, 1:])
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state2 = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state2, \
            hvd.allreduce(loss, op=hvd.Average)

    step = hvd.parallel.shard_step(
        local_step, in_specs=(P(), P(), P("hvd")),
        out_specs=(P(), P(), P()))
    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_remat_matches_no_remat(hvd8):
    cfg_r = dataclasses.replace(TINY, remat=True)
    tokens = jnp.asarray(np.random.RandomState(5).randint(0, 128, (1, 16)))
    params = Transformer(TINY).init(jax.random.PRNGKey(0), tokens)
    a = Transformer(TINY).apply(params, tokens)
    b = Transformer(cfg_r).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ring_striped_transformer_matches_dense(hvd8):
    """seq_parallel='ring_striped': striped tokens + automatic striped
    positions must reproduce the dense model's logits after unstriping."""
    from horovod_tpu.parallel.ring import stripe_sequence, unstripe_sequence
    cfg_s = dataclasses.replace(TINY, seq_parallel="ring_striped")
    model_d = Transformer(TINY)
    model_s = Transformer(cfg_s)
    tokens = jnp.asarray(np.random.RandomState(6).randint(0, 128, (2, 64)))
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    dense_logits = model_d.apply(params, tokens)
    striped_tokens = stripe_sequence(tokens, N)
    mesh = hvd8.mesh()
    sp_logits = jax.jit(jax.shard_map(
        lambda t: model_s.apply(params, t), mesh=mesh,
        in_specs=P(None, "hvd"), out_specs=P(None, "hvd")))(striped_tokens)
    np.testing.assert_allclose(
        np.asarray(unstripe_sequence(sp_logits, N)),
        np.asarray(dense_logits), rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# TPU stem optimizations (SpaceToDepthStem, max_pool_eq_grad) — numerics vs
# the naive formulations they replace.
# ---------------------------------------------------------------------------

def test_s2d_stem_matches_naive_conv():
    """SpaceToDepthStem is an exact re-indexing of conv 7x7/s2 SAME."""
    import flax.linen as nn
    from horovod_tpu.models.resnet import SpaceToDepthStem
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                    jnp.float32)
    stem = SpaceToDepthStem(features=16, dtype=jnp.float32)
    params = stem.init(jax.random.PRNGKey(1), x)
    ref = nn.Conv(16, (7, 7), (2, 2), padding="SAME", use_bias=False,
                  dtype=jnp.float32)
    y_s2d = stem.apply(params, x)
    y_ref = ref.apply({"params": {"kernel": params["params"]["kernel"]}}, x)
    assert y_s2d.shape == y_ref.shape == (2, 16, 16, 16)
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_max_pool_eq_grad_forward_and_backward():
    from horovod_tpu.models.resnet import max_pool_eq_grad
    import flax.linen as nn
    # Unique-maxima input: no ties, so the equality backward must equal
    # select_and_scatter's exactly.
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.permutation(2 * 12 * 12 * 3).reshape(2, 12, 12, 3),
                    jnp.float32)
    g_out = jnp.asarray(rng.randn(2, 6, 6, 3), jnp.float32)

    def naive(v):
        return jnp.sum(nn.max_pool(v, (3, 3), (2, 2), padding="SAME")
                       * g_out)

    def fast(v):
        return jnp.sum(max_pool_eq_grad(v) * g_out)

    np.testing.assert_allclose(np.asarray(max_pool_eq_grad(x)),
                               np.asarray(nn.max_pool(x, (3, 3), (2, 2),
                                                      padding="SAME")))
    np.testing.assert_allclose(np.asarray(jax.grad(fast)(x)),
                               np.asarray(jax.grad(naive)(x)),
                               rtol=1e-6, atol=1e-6)


def test_max_pool_eq_grad_ties_preserve_sum():
    """With ties the 1/n-per-tie convention must conserve the gradient
    sum (select_and_scatter routes it all to the first max instead)."""
    from horovod_tpu.models.resnet import max_pool_eq_grad
    x = jnp.ones((1, 8, 8, 1), jnp.float32)  # every window fully tied
    g_out = jnp.asarray(np.random.RandomState(3).rand(1, 4, 4, 1),
                        jnp.float32)

    def fast(v):
        return jnp.sum(max_pool_eq_grad(v) * g_out)

    grad = jax.grad(fast)(x)
    np.testing.assert_allclose(float(jnp.sum(grad)), float(jnp.sum(g_out)),
                               rtol=1e-6)


def test_max_pool_eq_grad_rejects_odd_extent():
    from horovod_tpu.models.resnet import max_pool_eq_grad
    with pytest.raises(ValueError, match="even"):
        jax.grad(lambda v: jnp.sum(max_pool_eq_grad(v)))(
            jnp.ones((1, 7, 8, 1), jnp.float32))


def test_resnet_fast_stem_matches_baseline_step():
    """fast_stem=True shares the param tree and reproduces the baseline
    forward logits (fp32, no ties in practice on random data)."""
    base = create_resnet50(num_classes=10, dtype=jnp.float32)
    fast = create_resnet50(num_classes=10, dtype=jnp.float32,
                           fast_stem=True)
    x = jnp.asarray(np.random.RandomState(4).randn(2, 64, 64, 3),
                    jnp.float32)
    variables = base.init(jax.random.PRNGKey(0), x, train=False)
    jax.tree_util.tree_map(lambda a, b: None, variables,
                           fast.init(jax.random.PRNGKey(0), x,
                                     train=False))  # identical tree
    y_base = base.apply(variables, x, train=False)
    y_fast = fast.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y_base),
                               rtol=2e-4, atol=2e-4)


# -- FusedBatchNorm (sync_batch_norm.py; VERDICT r4 #5 BN-chain fusion) ------

def _bn_pair(**kw):
    import flax.linen as nn
    from horovod_tpu.sync_batch_norm import FusedBatchNorm
    ref = nn.BatchNorm(momentum=0.9, epsilon=1e-5, dtype=jnp.float32, **kw)
    fused = FusedBatchNorm(momentum=0.9, epsilon=1e-5, dtype=jnp.float32,
                           **kw)
    return ref, fused


def test_fused_bn_matches_flax_batchnorm():
    """Same math, same param/stat tree as flax BatchNorm — the folded
    scale/offset formulation must be a pure reassociation."""
    x = jnp.asarray(np.random.RandomState(0).randn(8, 6, 6, 16)
                    .astype(np.float32))
    ref, fused = _bn_pair(use_running_average=False)
    vr = ref.init(jax.random.PRNGKey(0), x)
    vf = fused.init(jax.random.PRNGKey(0), x)
    assert jax.tree_util.tree_structure(vr) == \
        jax.tree_util.tree_structure(vf)
    params = jax.tree.map(lambda a: a + 0.3, vr["params"])  # nontrivial
    yr, mr = ref.apply({"params": params,
                        "batch_stats": vr["batch_stats"]}, x,
                       mutable=["batch_stats"])
    yf, mf = fused.apply({"params": params,
                          "batch_stats": vf["batch_stats"]}, x,
                         mutable=["batch_stats"])
    np.testing.assert_allclose(yr, yf, atol=5e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 mr["batch_stats"], mf["batch_stats"])
    # Eval mode reads the running stats identically.
    re_, fe = _bn_pair(use_running_average=True)
    ye = re_.apply({"params": params, "batch_stats": mr["batch_stats"]}, x)
    yfe = fe.apply({"params": params, "batch_stats": mf["batch_stats"]}, x)
    np.testing.assert_allclose(ye, yfe, atol=5e-6)


def test_fused_bn_sync_stats_one_psum(hvd8):
    """axis_name mode: cross-rank statistics match flax BatchNorm's, and
    the whole exchange is ONE all-reduce (concatenated sum/sumsq/count;
    the reference allreduces mean and variance separately,
    tensorflow/sync_batch_norm.py:22)."""
    import re as _re
    shard_map = jax.shard_map
    ref, fused = _bn_pair(use_running_average=False, axis_name="hvd")
    x = jnp.asarray(np.random.RandomState(0).randn(8, 4, 4, 8)
                    .astype(np.float32))
    v = fused.init(jax.random.PRNGKey(0), x[:1])
    mesh = hvd.mesh()

    def make(step_bn):
        def local(xb):
            y, mut = step_bn.apply(
                {"params": v["params"], "batch_stats": v["batch_stats"]},
                xb, mutable=["batch_stats"])
            return y, mut["batch_stats"]["mean"]
        return jax.jit(shard_map(local, mesh=mesh,
                                 in_specs=P("hvd"), out_specs=(P("hvd"),
                                                               P())))

    yr, mean_r = make(ref)(x)
    yf, mean_f = make(fused)(x)
    np.testing.assert_allclose(yr, yf, atol=5e-6)
    np.testing.assert_allclose(mean_r, mean_f, atol=1e-6)
    hlo = make(fused).lower(x).as_text()
    assert len(_re.findall(r"stablehlo\.all_reduce", hlo)) == 1


def test_resnet_fused_bn_keeps_activations_bf16():
    """The BN-chain fusion claim, pinned at the StableHLO level (what the
    TPU compiler receives; the CPU backend promotes bf16 wholesale, so
    optimized CPU HLO cannot show it): with FusedBatchNorm the bf16
    ResNet's full-tensor elementwise work stays bf16 — no per-BN
    f32 upcast/normalize/downcast chain (PERF_r02's BN-chain headroom)."""
    import re as _re
    from horovod_tpu.models.resnet import ResNet

    x = jnp.asarray(np.random.RandomState(0).rand(4, 32, 32, 3)
                    .astype(np.float32))
    y = jnp.asarray(np.random.RandomState(1).randint(0, 10, size=(4,)))

    def lowered(fused):
        model = ResNet(stage_sizes=[1, 1], num_classes=10, num_filters=16,
                       dtype=jnp.bfloat16, fused_bn=fused)
        v = model.init(jax.random.PRNGKey(0), x, train=False)

        def loss_fn(p, bs):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, mut

        step = jax.jit(
            lambda p, bs: jax.value_and_grad(loss_fn, has_aux=True)(p, bs))
        return step.lower(v["params"], v["batch_stats"]).as_text()

    def counts(txt):
        f32 = len(_re.findall(
            r"stablehlo\.(multiply|add|subtract)\s.*"
            r"tensor<\d+x\d+x\d+x\d+xf32>", txt))
        bf16 = len(_re.findall(
            r"stablehlo\.(multiply|add|subtract)\s.*"
            r"tensor<\d+x\d+x\d+x\d+xbf16>", txt))
        return f32, bf16

    flax_f32, flax_bf16 = counts(lowered(False))
    fused_f32, fused_bf16 = counts(lowered(True))
    # Measured at round 5: flax 194/9, fused 46/85.  Assert the structure,
    # not the exact numbers.
    assert fused_f32 < flax_f32 / 2, (fused_f32, flax_f32)
    assert fused_bf16 > flax_bf16 * 3, (fused_bf16, flax_bf16)


def test_resnet_fused_bn_param_tree_compatible():
    """fused_bn must not change the checkpoint surface: identical
    param/batch_stats trees and near-identical step numerics."""
    from horovod_tpu.models.resnet import ResNet
    x = jnp.asarray(np.random.RandomState(0).rand(2, 16, 16, 3)
                    .astype(np.float32))
    vs = []
    for fused in (False, True):
        model = ResNet(stage_sizes=[1], num_classes=4, num_filters=8,
                       dtype=jnp.float32, fused_bn=fused)
        vs.append(model.init(jax.random.PRNGKey(0), x, train=False))
    assert jax.tree_util.tree_structure(vs[0]) == \
        jax.tree_util.tree_structure(vs[1])
    # Same params -> same output (f32 so tolerances are tight).
    m0 = ResNet(stage_sizes=[1], num_classes=4, num_filters=8,
                dtype=jnp.float32, fused_bn=False)
    m1 = ResNet(stage_sizes=[1], num_classes=4, num_filters=8,
                dtype=jnp.float32, fused_bn=True)
    y0, _ = m0.apply(vs[0], x, train=True, mutable=["batch_stats"])
    y1, _ = m1.apply(vs[0], x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(y0, y1, atol=2e-5)


def test_sync_batch_stats_arbitrary_reduction_axes(hvd8):
    """The one-psum concat must not narrow the public contract: stats of
    any rank (any reduction_axes) ride the single collective."""
    x = jnp.asarray(np.random.RandomState(0).randn(8, 4, 6, 3)
                    .astype(np.float32))

    def f(xb):
        return hvd.sync_batch_stats(xb, reduction_axes=(0, 1))

    step = jax.jit(jax.shard_map(
        f, mesh=hvd8.mesh(), in_specs=P("hvd"), out_specs=(P(), P())))
    m, v = step(x)
    assert m.shape == (6, 3)
    np.testing.assert_allclose(m, x.mean(axis=(0, 1)), atol=1e-5)
    np.testing.assert_allclose(v, x.var(axis=(0, 1)), atol=1e-5)


# -- scan_layers (lax.scan over blocks: ~L x faster compile) -----------------

def test_scan_layers_matches_unrolled(hvd8):
    """Identical numerics, fwd and grad, with params migrated by
    stack_block_params; unstack round-trips."""
    from horovod_tpu.models import (stack_block_params,
                                    unstack_block_params)
    cfg_s = dataclasses.replace(TINY, scan_layers=True)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    m_u, m_s = Transformer(TINY), Transformer(cfg_s)
    p_u = m_u.init(jax.random.PRNGKey(0), toks)
    p_s = {"params": stack_block_params(p_u["params"], TINY.num_layers)}
    np.testing.assert_allclose(m_u.apply(p_u, toks), m_s.apply(p_s, toks),
                               atol=2e-5)

    def loss(m):
        return lambda p: lm_loss(m.apply(p, toks)[:, :-1], toks[:, 1:])

    gu = jax.grad(loss(m_u))(p_u)
    gs = jax.grad(loss(m_s))(p_s)
    gs_unrolled = unstack_block_params(gs["params"])
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=3e-5),
                 jax.tree.map(np.asarray, gu["params"]),
                 jax.tree.map(np.asarray, gs_unrolled))
    # Round-trip of the migration itself.
    rt = unstack_block_params(p_s["params"])
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b),
                 jax.tree.map(np.asarray, p_u["params"]),
                 jax.tree.map(np.asarray, rt))


def test_scan_layers_shrinks_program(hvd8):
    """The compile-time claim's proxy: the lowered program must carry ONE
    block body, not num_layers copies (24-layer measurement: 59.7->5.2 s
    CPU compile; sizes are the deterministic pin)."""
    cfg = dataclasses.replace(TINY, num_layers=8)
    cfg_s = dataclasses.replace(cfg, scan_layers=True)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))

    def lowered_size(c):
        m = Transformer(c)
        v = m.init(jax.random.PRNGKey(0), toks)
        f = jax.grad(lambda p: lm_loss(m.apply(p, toks)[:, :-1],
                                       toks[:, 1:]))
        return len(jax.jit(f).lower(v).as_text())

    assert lowered_size(cfg_s) < lowered_size(cfg) / 2


def test_scan_layers_remat_matches(hvd8):
    cfg_s = dataclasses.replace(TINY, scan_layers=True)
    cfg_sr = dataclasses.replace(TINY, scan_layers=True, remat=True)
    toks = jnp.asarray(np.random.RandomState(1).randint(0, 128, (1, 16)))
    params = Transformer(cfg_s).init(jax.random.PRNGKey(0), toks)
    a = Transformer(cfg_s).apply(params, toks)
    b = Transformer(cfg_sr).apply(params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_scan_layers_seq_parallel_matches_dense(hvd8):
    """scan over blocks containing ring-attention collectives (ppermute
    inside the scan body under shard_map) must still match dense."""
    cfg_sp = dataclasses.replace(TINY, scan_layers=True,
                                 seq_parallel="ring")
    model_d = Transformer(TINY)
    model_s = Transformer(cfg_sp)
    toks = jnp.asarray(np.random.RandomState(3).randint(0, 128, (2, 64)))
    from horovod_tpu.models import stack_block_params
    p_u = model_d.init(jax.random.PRNGKey(0), toks)
    p_s = {"params": stack_block_params(p_u["params"], TINY.num_layers)}
    dense_logits = model_d.apply(p_u, toks)
    positions = jnp.arange(64)[None, :].repeat(2, axis=0)
    sp_logits = jax.jit(jax.shard_map(
        lambda t, pos: model_s.apply(p_s, t, positions=pos),
        mesh=hvd8.mesh(),
        in_specs=(P(None, "hvd"), P(None, "hvd")),
        out_specs=P(None, "hvd")))(toks, positions)
    np.testing.assert_allclose(np.asarray(sp_logits),
                               np.asarray(dense_logits),
                               rtol=2e-3, atol=2e-3)


def test_scan_layers_rejects_interleaved_moe(hvd8):
    cfg = dataclasses.replace(TINY, scan_layers=True, moe_experts=4,
                              moe_every=2)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 128, (1, 16)))
    with pytest.raises(ValueError, match="homogeneous"):
        Transformer(cfg).init(jax.random.PRNGKey(0), toks)
