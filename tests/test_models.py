"""Model zoo tests: shapes, training steps, sequence-parallel equivalence."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import (
    TransformerConfig, Transformer, create_bert, create_gpt2, lm_loss,
    create_resnet50)

N = 8

TINY = TransformerConfig(vocab_size=128, num_layers=2, num_heads=8,
                         d_model=64, d_ff=128, max_len=64, causal=True,
                         dtype=jnp.float32)


def test_resnet50_forward_shape(hvd8):
    model = create_resnet50(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_gpt_forward_and_loss(hvd8):
    model = Transformer(TINY)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32
    loss = lm_loss(logits[:, :-1], tokens[:, 1:])
    assert float(loss) > 0


def test_gpt_causality(hvd8):
    """Changing a future token must not affect past logits."""
    model = Transformer(TINY)
    rng = np.random.RandomState(1)
    t1 = rng.randint(0, 128, (1, 16))
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 1) % 128  # perturb only the last token
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
    l1 = model.apply(params, jnp.asarray(t1))
    l2 = model.apply(params, jnp.asarray(t2))
    np.testing.assert_allclose(np.asarray(l1[:, :-1]),
                               np.asarray(l2[:, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[:, -1]), np.asarray(l2[:, -1]))


def test_bert_bidirectional(hvd8):
    cfg = dataclasses.replace(TINY, causal=False)
    model = Transformer(cfg)
    rng = np.random.RandomState(2)
    t1 = rng.randint(0, 128, (1, 16))
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 1) % 128
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
    l1 = model.apply(params, jnp.asarray(t1))
    l2 = model.apply(params, jnp.asarray(t2))
    # bidirectional: early positions DO see the change
    assert not np.allclose(np.asarray(l1[:, 0]), np.asarray(l2[:, 0]))


def test_factory_configs(hvd8):
    assert create_gpt2("medium").cfg.num_layers == 24
    assert create_gpt2("medium").cfg.d_model == 1024
    assert create_bert("large").cfg.num_layers == 24
    assert not create_bert("large").cfg.causal
    assert create_bert("base").cfg.vocab_size == 30522


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_seq_parallel_transformer_matches_dense(hvd8, mode):
    """Sequence-parallel attention inside the full model must match the
    dense model exactly (same params, sharded sequence)."""
    cfg_dense = TINY
    cfg_sp = dataclasses.replace(TINY, seq_parallel=mode)
    model_d = Transformer(cfg_dense)
    model_s = Transformer(cfg_sp)
    tokens = jnp.asarray(np.random.RandomState(3).randint(0, 128, (2, 64)))
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    dense_logits = model_d.apply(params, tokens)

    mesh = hvd8.mesh()
    positions = jnp.arange(64)[None, :].repeat(2, axis=0)

    def shard_fwd(tokens, positions):
        return model_s.apply(params, tokens, positions=positions)

    sp_logits = jax.jit(jax.shard_map(
        shard_fwd, mesh=mesh,
        in_specs=(P(None, "hvd"), P(None, "hvd")),
        out_specs=P(None, "hvd")))(tokens, positions)
    np.testing.assert_allclose(np.asarray(sp_logits),
                               np.asarray(dense_logits),
                               rtol=2e-3, atol=2e-3)


def test_gpt_train_step_decreases_loss(hvd8):
    model = Transformer(TINY)
    rng = np.random.RandomState(4)
    tokens = jnp.asarray(rng.randint(0, 128, (8, 32)))
    params = model.init(jax.random.PRNGKey(0), tokens[:1])
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
    state = opt.init(params)

    def local_step(params, state, toks):
        def loss_fn(p):
            logits = model.apply(p, toks)
            return lm_loss(logits[:, :-1], toks[:, 1:])
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state2 = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state2, \
            hvd.allreduce(loss, op=hvd.Average)

    step = hvd.parallel.shard_step(
        local_step, in_specs=(P(), P(), P("hvd")),
        out_specs=(P(), P(), P()))
    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_remat_matches_no_remat(hvd8):
    cfg_r = dataclasses.replace(TINY, remat=True)
    tokens = jnp.asarray(np.random.RandomState(5).randint(0, 128, (1, 16)))
    params = Transformer(TINY).init(jax.random.PRNGKey(0), tokens)
    a = Transformer(TINY).apply(params, tokens)
    b = Transformer(cfg_r).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ring_striped_transformer_matches_dense(hvd8):
    """seq_parallel='ring_striped': striped tokens + automatic striped
    positions must reproduce the dense model's logits after unstriping."""
    from horovod_tpu.parallel.ring import stripe_sequence, unstripe_sequence
    cfg_s = dataclasses.replace(TINY, seq_parallel="ring_striped")
    model_d = Transformer(TINY)
    model_s = Transformer(cfg_s)
    tokens = jnp.asarray(np.random.RandomState(6).randint(0, 128, (2, 64)))
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    dense_logits = model_d.apply(params, tokens)
    striped_tokens = stripe_sequence(tokens, N)
    mesh = hvd8.mesh()
    sp_logits = jax.jit(jax.shard_map(
        lambda t: model_s.apply(params, t), mesh=mesh,
        in_specs=P(None, "hvd"), out_specs=P(None, "hvd")))(striped_tokens)
    np.testing.assert_allclose(
        np.asarray(unstripe_sequence(sp_logits, N)),
        np.asarray(dense_logits), rtol=2e-3, atol=2e-3)
