"""Checkpoint helpers: rank-0 save + broadcast restore (SURVEY.md §5.4)."""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd


def test_save_restore_roundtrip(tmp_path, hvd8):
    state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.asarray(7)}
    path = str(tmp_path / "ckpt")
    hvd.checkpoint.save(path, state)
    restored = hvd.checkpoint.restore(path, template=state)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert int(restored["step"]) == 7


def test_restore_without_template_single(tmp_path, hvd8):
    state = {"a": jnp.ones((3,))}
    path = str(tmp_path / "ckpt2")
    hvd.checkpoint.save(path, state)
    restored = hvd.checkpoint.restore(path)
    np.testing.assert_allclose(np.asarray(restored["a"]), np.ones(3))
