"""Checkpoint helpers: rank-0 save + broadcast restore (SURVEY.md §5.4)."""

import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.compat import has_vma_tracking

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MULTIPROC_WORKER = '''
import os
import sys
sys.path.insert(0, r"{repo}")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax
jax.config.update("jax_platforms", "cpu")
import optax
import horovod_tpu as hvd

hvd.init()
path = os.path.join(r"{ckpt_dir}", "model")
params = {{"w": jax.numpy.ones((4,)) * (1.0 if hvd.rank() == 0 else 99.0)}}
base = optax.sgd(0.1, momentum=0.9)
opt = hvd.DistributedOptimizer(base)
opt_state = opt.init(params)
# rank 0 writes; the extra.json sidecar must exist before ANY rank is
# released from save's barrier, so the coordinated immediate load sees it.
hvd.checkpoint.save_model(path, params, opt_state, extra={{"epoch": 7}})
p, o, os_, extra = hvd.checkpoint.load_model(path, optimizer=base,
                                             params_template=params)
assert extra == {{"epoch": 7}}, f"rank {{hvd.rank()}} got extra={{extra}}"
assert float(p["w"][0]) == 1.0, "did not adopt rank 0 params"
print(f"CKPT_OK rank={{hvd.rank()}}")
'''


@pytest.mark.integration
@pytest.mark.xdist_group("heavy_e2e")
def test_save_model_load_model_two_processes(tmp_path):
    """Real 2-process world (launcher + jax.distributed): rank-0-only
    orbax write must not deadlock against the release barrier (orbax's own
    multihost sync is scoped to the writing process — see _ckptr), and the
    sidecar is visible to the immediate coordinated load on both ranks."""
    script = tmp_path / "ckpt_worker.py"
    script.write_text(MULTIPROC_WORKER.format(ckpt_dir=str(tmp_path),
                                              repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CKPT_OK rank=0" in proc.stdout
    assert "CKPT_OK rank=1" in proc.stdout


def test_save_restore_roundtrip(tmp_path, hvd8):
    state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.asarray(7)}
    path = str(tmp_path / "ckpt")
    hvd.checkpoint.save(path, state)
    restored = hvd.checkpoint.restore(path, template=state)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert int(restored["step"]) == 7


def test_restore_without_template_single(tmp_path, hvd8):
    state = {"a": jnp.ones((3,))}
    path = str(tmp_path / "ckpt2")
    hvd.checkpoint.save(path, state)
    restored = hvd.checkpoint.restore(path)
    np.testing.assert_allclose(np.asarray(restored["a"]), np.ones(3))


@pytest.mark.skipif(
    not has_vma_tracking(),
    reason="mid-cycle exactness requires vma semantics: only when the "
           "shard_map transpose pre-reduces replicated-param gradients is "
           "the accumulator truly replicated — on old jax it is per-device "
           "local, which a replicated-state checkpoint cannot capture "
           "(see horovod_tpu/compat.py)")
def test_load_model_resumes_identical_trajectory(tmp_path, hvd8):
    """save_model/load_model (keras/__init__.py:268 analog): restore the
    wrapped optimizer's FULL state — adam moments AND the local gradient-
    aggregation counter mid-cycle — and the continued run must reproduce
    the uninterrupted run's losses exactly."""
    import jax
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.models import create_mlp

    model = create_mlp(features=(16, 4))
    X = jnp.asarray(np.random.RandomState(0).randn(16, 8).astype(np.float32))
    Y = jnp.asarray(np.random.RandomState(1).randn(16, 4).astype(np.float32))
    params0 = model.init(jax.random.PRNGKey(0), X[:1])

    def make(opt_state=None, params=None):
        opt = hvd8.DistributedOptimizer(optax.adam(1e-2),
                                        backward_passes_per_step=2)
        params = params if params is not None else params0
        opt_state = opt_state if opt_state is not None else opt.init(params)

        def local_step(p, s, xb, yb):
            loss, g = jax.value_and_grad(
                lambda p: jnp.mean((model.apply(p, xb) - yb) ** 2))(p)
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, hvd8.allreduce(
                loss, op=hvd8.Average)

        step = hvd8.parallel.shard_step(
            local_step, in_specs=(P(), P(), P("hvd"), P("hvd")),
            out_specs=(P(), P(), P()))
        return opt, params, opt_state, step

    # Uninterrupted reference run: 3 steps (ODD — the accumulation cycle
    # of backward_passes_per_step=2 is mid-flight at the save point), then
    # 4 more.
    _, p, s, step = make()
    for _ in range(3):
        p, s, _loss = step(p, s, X, Y)
    ref_losses = []
    for _ in range(4):
        p, s, loss = step(p, s, X, Y)
        ref_losses.append(float(loss))

    # Interrupted run: same 3 steps, save_model, load_model, 4 more.
    _, p, s, step = make()
    for _ in range(3):
        p, s, _loss = step(p, s, X, Y)
    path = str(tmp_path / "model_ckpt")
    hvd8.checkpoint.save_model(path, p, s, extra={"epoch": 3})
    params_r, opt_r, state_r, extra = hvd8.checkpoint.load_model(
        path, optimizer=optax.adam(1e-2), params_template=params0,
        backward_passes_per_step=2)
    assert extra == {"epoch": 3}
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)), p,
        params_r)
    _, p2, s2, step2 = make(opt_state=state_r, params=params_r)
    resumed = []
    for _ in range(4):
        p2, s2, loss = step2(p2, s2, X, Y)
        resumed.append(float(loss))
    np.testing.assert_allclose(resumed, ref_losses, rtol=0, atol=0)
