"""DistributedOptimizer / gradient layer tests (reference:
test/parallel/test_torch.py optimizer sections + gradient_aggregation tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.compat import has_vma_tracking
from tests.test_collective_ops import run_spmd

N = 8

# reduce_axes needs real varying-manual-axes tracking to tell local
# gradients from pre-summed ones; on a shimmed old jax the optimizer
# refuses loudly (by design) instead of guessing — the capability, not
# the code, is absent here.
requires_vma = pytest.mark.skipif(
    not has_vma_tracking(),
    reason="DistributedOptimizer(reduce_axes=...) requires jax vma "
           "tracking (unavailable on this jax; see horovod_tpu/compat.py)")


def test_distributed_optimizer_averages_gradients(hvd8):
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    params = {"w": jnp.zeros((3,), jnp.float32)}
    per_rank_grads = jnp.asarray(
        np.random.RandomState(0).randn(N, 3).astype(np.float32))

    def body(g):
        state = opt.init(params)
        updates, _ = opt.update({"w": g}, state, params)
        return updates["w"]

    out = run_spmd(hvd8, body, per_rank_grads)
    expected = -np.mean(np.asarray(per_rank_grads), axis=0)
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r]), expected, rtol=1e-5)


def test_distributed_optimizer_sum_and_predivide(hvd8):
    opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                   gradient_predivide_factor=2.0)
    params = {"w": jnp.zeros((4,), jnp.float32)}
    g = jnp.asarray(np.random.RandomState(1).randn(N, 4).astype(np.float32))

    def body(gr):
        state = opt.init(params)
        updates, _ = opt.update({"w": gr}, state, params)
        return updates["w"]

    out = run_spmd(hvd8, body, g)
    # predivide 2: prescale 1/2, average, postscale 2 → same as plain average.
    expected = -np.mean(np.asarray(g), axis=0)
    np.testing.assert_allclose(np.asarray(out[0]), expected, rtol=1e-4)


def test_backward_passes_per_step_accumulates(hvd8):
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    params = {"w": jnp.zeros((2,), jnp.float32)}
    g1 = jnp.asarray(np.random.RandomState(2).randn(N, 2).astype(np.float32))
    g2 = jnp.asarray(np.random.RandomState(3).randn(N, 2).astype(np.float32))

    def body(a, b):
        state = opt.init(params)
        u1, state = opt.update({"w": a}, state, params)
        u2, state = opt.update({"w": b}, state, params)
        return u1["w"], u2["w"]

    u1, u2 = run_spmd(hvd8, body, g1, g2)
    # First pass: zero update (aggregation only).
    np.testing.assert_allclose(np.asarray(u1[0]), np.zeros(2), atol=1e-7)
    # Second: mean over ranks of (g1+g2)/2, negated by sgd(1.0).
    expected = -np.mean((np.asarray(g1) + np.asarray(g2)) / 2, axis=0)
    np.testing.assert_allclose(np.asarray(u2[0]), expected, rtol=1e-4)


def test_value_and_grad_wrapper(hvd8):
    per_rank_x = jnp.asarray(
        np.random.RandomState(4).randn(N, 5).astype(np.float32))

    def body(x):
        def loss(w):
            return jnp.sum(w * x)
        val, g = hvd.value_and_grad(loss)(jnp.ones((5,), jnp.float32))
        return g

    out = run_spmd(hvd8, body, per_rank_x)
    expected = np.mean(np.asarray(per_rank_x), axis=0)
    np.testing.assert_allclose(np.asarray(out[0]), expected, rtol=1e-5)


def test_grad_wrapper_sum(hvd8):
    per_rank_x = jnp.asarray(
        np.random.RandomState(5).randn(N, 3).astype(np.float32))

    def body(x):
        g = hvd.grad(lambda w: jnp.sum(w * x), op=hvd.Sum)(
            jnp.ones((3,), jnp.float32))
        return g

    out = run_spmd(hvd8, body, per_rank_x)
    np.testing.assert_allclose(np.asarray(out[0]),
                               np.sum(np.asarray(per_rank_x), 0), rtol=1e-5)


def test_adasum_delta_step_ranks_agree(hvd8):
    opt = optax.sgd(0.5)
    params = {"w": jnp.ones((4,), jnp.float32)}
    g = jnp.asarray(np.random.RandomState(6).randn(N, 4).astype(np.float32))

    def body(gr):
        state = opt.init(params)
        new_params, _ = hvd.adasum_delta_step(opt, params, {"w": gr}, state)
        return new_params["w"]

    out = np.asarray(run_spmd(hvd8, body, g))
    for r in range(1, N):
        np.testing.assert_allclose(out[r], out[0], rtol=1e-5)
    assert not np.allclose(out[0], np.ones(4))  # something happened


def test_optimizer_num_groups(hvd8):
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), num_groups=2)
    params = {"a": jnp.zeros((2,)), "b": jnp.zeros((3,)),
              "c": jnp.zeros((4,))}
    rng = np.random.RandomState(7)
    ga = jnp.asarray(rng.randn(N, 2).astype(np.float32))
    gb = jnp.asarray(rng.randn(N, 3).astype(np.float32))
    gc = jnp.asarray(rng.randn(N, 4).astype(np.float32))

    def body(a, b, c):
        state = opt.init(params)
        updates, _ = opt.update({"a": a, "b": b, "c": c}, state, params)
        return updates["a"], updates["b"], updates["c"]

    ua, ub, uc = run_spmd(hvd8, body, ga, gb, gc)
    np.testing.assert_allclose(np.asarray(ua[0]),
                               -np.mean(np.asarray(ga), 0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(uc[0]),
                               -np.mean(np.asarray(gc), 0), rtol=1e-5)


def test_broadcast_variables_tree(hvd8):
    params = {"w": jnp.full((3, 2), 5.0), "b": jnp.arange(4.0)}
    out = hvd.broadcast_variables(params, root_rank=0)
    assert out["w"].shape == (3, 2)
    np.testing.assert_allclose(out["b"], np.arange(4.0))


def test_broadcast_optimizer_state(hvd8):
    opt = optax.adam(1e-3)
    state = opt.init({"w": jnp.ones((3,))})
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    leaves = jax.tree_util.tree_leaves(out)
    assert len(leaves) == len(jax.tree_util.tree_leaves(state))


def test_broadcast_object_and_allgather_object(hvd8):
    obj = {"epoch": 3, "lr": 0.1}
    assert hvd.broadcast_object(obj) == obj  # emulated: shared process
    objs = hvd.allgather_object([{"r": r} for r in range(N)])
    assert objs == [{"r": r} for r in range(N)]
    with pytest.raises(ValueError):
        hvd.allgather_object({"not": "a list"})


def test_sync_batch_stats(hvd8):
    x = np.random.RandomState(8).randn(N, 16, 4).astype(np.float32)

    def body(xb):
        mean, var = hvd.sync_batch_stats(xb)
        return mean, var

    mean, var = run_spmd(hvd8, body, jnp.asarray(x))
    flat = x.reshape(-1, 4)
    np.testing.assert_allclose(np.asarray(mean[0]), flat.mean(0), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(var[0]), flat.var(0),
                               rtol=1e-3, atol=1e-4)


def test_shard_step_helper(hvd8):
    step = hvd.parallel.shard_step(
        lambda w, xb: hvd.allreduce(jnp.sum(xb) * w, op=hvd.Sum),
        in_specs=(P(), P("hvd")), out_specs=P())
    x = jnp.ones((8, 2), jnp.float32)
    out = step(jnp.asarray(2.0), x)
    np.testing.assert_allclose(np.asarray(out), 2.0 * 16.0)


def test_make_mesh_and_hierarchical(hvd8):
    m = hvd.parallel.make_mesh({"cross": 2, "local": 4})
    assert m.shape == {"cross": 2, "local": 4}
    with pytest.raises(ValueError):
        hvd.parallel.make_mesh({"a": 3})
    hm = hvd.parallel.hierarchical_mesh()
    assert int(np.prod(list(hm.shape.values()))) == N


def test_invariant_grads_not_double_counted(hvd8):
    """shard_map's transpose pre-sums grads of replicated params (vma
    semantics); the optimizer layer must not psum them again."""
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    x = jnp.asarray(np.random.RandomState(9).randn(N, 5).astype(np.float32))

    def body(xr):
        params = {"w": jnp.ones((5,), jnp.float32)}  # replicated/invariant
        # grads wrt invariant params arrive already globally summed:
        # grad = sum_r x_r.  Average must yield mean_r x_r, not psum it again
        # (which would give N * sum_r x_r).
        grads = jax.grad(lambda p: jnp.sum(p["w"] * xr))(params)
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        return updates["w"]

    out = run_spmd(hvd8, body, x)
    expected = -np.mean(np.asarray(x), axis=0)
    np.testing.assert_allclose(np.asarray(out[0]), expected, rtol=1e-5)


def test_tape_local_grads_average_exactly(hvd8):
    x = jnp.asarray(np.random.RandomState(10).randn(N, 4).astype(np.float32))

    def body(xr):
        w = jnp.ones((4,), jnp.float32)
        val, g = hvd.value_and_grad(lambda w: jnp.sum(w * xr))(w)
        return g

    out = run_spmd(hvd8, body, x)
    np.testing.assert_allclose(np.asarray(out[0]),
                               np.mean(np.asarray(x), 0), rtol=1e-5)


def test_partial_distributed_optimizer(hvd8):
    """Parameters matched by local_filter keep their LOCAL gradients
    (PartialDistributedOptimizer, tensorflow/__init__.py:1204)."""
    opt = hvd.PartialDistributedOptimizer(
        optax.sgd(1.0),
        local_filter=lambda path, leaf: "local" in str(path[0]))
    params = {"shared": jnp.zeros((3,)), "local_emb": jnp.zeros((3,))}
    g = jnp.asarray(np.random.RandomState(11).randn(N, 3).astype(np.float32))

    def body(gr):
        state = opt.init(params)
        # make both grads VARYING per-slot values
        updates, _ = opt.update({"shared": gr, "local_emb": gr}, state,
                                params)
        return updates["shared"], updates["local_emb"]

    shared, local = run_spmd(hvd8, body, g)
    arr = np.asarray(g)
    # shared: averaged over ranks (same on all slots)
    np.testing.assert_allclose(np.asarray(shared[0]), -arr.mean(0),
                               rtol=1e-5)
    # local: each slot keeps its own gradient
    for r in range(N):
        np.testing.assert_allclose(np.asarray(local[r]), -arr[r], rtol=1e-5)


# ---------------------------------------------------------------------------
# 2-D mesh sugar: reduce_axes spans exactly the listed mesh axes
# ---------------------------------------------------------------------------

@requires_vma
def test_reduce_axes_2d_mesh_average():
    """DistributedOptimizer(reduce_axes=('dp','sp')) inside a dp×sp
    shard_map: varying grads are averaged over BOTH axes; pre-reduced
    (invariant) grads are normalized, not re-summed."""
    import jax
    import optax
    from jax.sharding import Mesh, PartitionSpec as P
    import horovod_tpu as hvd

    dp, sp = 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:dp * sp]).reshape(dp, sp),
                ("dp", "sp"))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), reduce_axes=("dp", "sp"))
    g = jnp.asarray(np.random.RandomState(3).randn(dp * sp, 5)
                    .astype(np.float32))
    params = {"w": jnp.zeros((5,))}

    def body(gr):
        # gr: [1, 5] local shard (dim0 split over BOTH axes) -> a per-shard
        # VARYING gradient
        state = opt.init(params)
        updates, _ = opt.update({"w": gr[0]}, state, params)
        return jax.lax.pmean(jax.lax.pmean(updates["w"], "sp"), "dp")

    out = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(("dp", "sp")),),
        out_specs=P()))(g)
    np.testing.assert_allclose(np.asarray(out), -np.asarray(g).mean(0),
                               rtol=1e-5)


@requires_vma
def test_reduce_axes_invariant_leaf_normalized():
    """A gradient that the shard_map transpose already globally summed
    (replicated parameter) must be divided by dp*sp, not psum'd again."""
    import jax
    import optax
    from jax.sharding import Mesh, PartitionSpec as P
    import horovod_tpu as hvd

    dp, sp = 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:dp * sp]).reshape(dp, sp),
                ("dp", "sp"))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), reduce_axes=("dp", "sp"))
    x = jnp.asarray(np.random.RandomState(5).randn(dp * sp, 3)
                    .astype(np.float32))
    w0 = jnp.ones((3,))

    def body(w, xb):
        def loss(p):
            return jnp.sum(p * xb[0])   # per-shard loss on the local row
        g = jax.grad(loss)(w)        # transpose pre-sums over ALL shards
        state = opt.init(w)
        updates, _ = opt.update(g, state, w)
        return updates

    out = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(), P(("dp", "sp"))),
        out_specs=P()))(w0, x)
    # sum of per-shard grads (= sum of rows) averaged over dp*sp shards
    np.testing.assert_allclose(np.asarray(out),
                               -np.asarray(x).mean(0), rtol=1e-5)


def test_reduce_axes_outside_mesh_raises():
    import optax
    import horovod_tpu as hvd
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), reduce_axes=("dp",))
    with pytest.raises(ValueError, match="not bound"):
        opt.update({"w": jnp.ones((2,))}, opt.init({"w": jnp.ones((2,))}),
                   {"w": jnp.ones((2,))})


@requires_vma
def test_reduce_axes_param_sharded_leaf_not_summed_over_its_axis():
    """A parameter SHARDED over one of the reduce axes (expert/tensor-
    parallel leaf) must have its gradient psum'd only over the remaining
    axes — summing over the shard axis would mix different parameters —
    while AVERAGE still divides by the full dp*ep degree."""
    import jax
    import optax
    from jax.sharding import Mesh, PartitionSpec as P
    import horovod_tpu as hvd

    dp, ep = 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:dp * ep]).reshape(dp, ep),
                ("dp", "ep"))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), reduce_axes=("dp", "ep"))
    # one "expert row" per (dp, ep) cell; parameter sharded over ep
    g = jnp.asarray(np.random.RandomState(7).randn(dp, ep, 3)
                    .astype(np.float32))
    w = jnp.zeros((ep, 3), jnp.float32)

    def body(wl, gl):
        # wl: [1, 3] this ep-shard's expert; gl: [1, 1, 3] local grad
        state = opt.init({"e": wl})
        updates, _ = opt.update({"e": gl[0]}, state, {"e": wl})
        return updates["e"]

    out = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("ep"), P("dp", "ep")),
        out_specs=P("ep")))(w, g)   # [ep, 3] reassembled over shards
    # expected: -(sum over dp of g) / (dp * ep), per ep shard
    want = -np.asarray(g).sum(axis=0) / (dp * ep)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)
