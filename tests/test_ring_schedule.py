"""Double-buffered ring schedule (ISSUE 1): the overlapped schedule must
match the serial schedule — forward and all three gradients, every mask
mode, f32 and bf16 — the contiguous-causal skip branch must provably never
invoke the flash kernel, the double-buffered ``_ring_reduce`` must stay
exact, and the per-hop timeline events must land in the trace."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd  # noqa: F401  (installs jax API shims)
from horovod_tpu.parallel import ring as ring_mod
from horovod_tpu.parallel.ring import (ring_attention, ring_flash_attention,
                                       stripe_sequence)

N = 8
MASK_MODES = [(False, False), (True, False), (True, True)]  # (causal, striped)


def _qkv(seed, B=2, S=64, H=4, D=16, dtype=np.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D).astype(dtype) * 0.3)
    return mk(), mk(), mk()


def _runner(hvd_mod, fn, causal, striped, schedule, **kw):
    """fwd + (dq, dk, dv) for the given ring fn/config, sharded over hvd."""
    def run(q, k, v):
        def loss(q, k, v):
            return jnp.mean(fn(q, k, v, axis_name="hvd", causal=causal,
                               striped=striped, schedule=schedule, **kw) ** 2)
        return (fn(q, k, v, axis_name="hvd", causal=causal, striped=striped,
                   schedule=schedule, **kw),
                *jax.grad(loss, argnums=(0, 1, 2))(q, k, v))
    return jax.jit(jax.shard_map(
        run, mesh=hvd_mod.mesh(), in_specs=(P(None, "hvd"),) * 3,
        out_specs=(P(None, "hvd"),) * 4, check_vma=False))


@pytest.mark.parametrize("causal,striped", MASK_MODES)
def test_ring_attention_overlap_matches_serial(hvd8, causal, striped):
    """Double-buffered overlap (+ true skip on contiguous-causal hops) vs
    the legacy serial schedule: same fold order, same values — forward and
    all three gradients within the existing ring test tolerances."""
    q, k, v = _qkv(0)
    if striped:
        q, k, v = (stripe_sequence(t, N) for t in (q, k, v))
    serial = _runner(hvd8, ring_attention, causal, striped, "serial")(q, k, v)
    overlap = _runner(hvd8, ring_attention, causal, striped,
                      "overlap")(q, k, v)
    for a, b in zip(serial, overlap):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.parametrize(
    "causal,striped",
    [(False, False),
     # causal flash variants ~20s each on the tier-1 box: nightly tier
     pytest.param(True, False, marks=pytest.mark.slow),
     pytest.param(True, True, marks=pytest.mark.slow)])
def test_ring_flash_overlap_matches_serial(hvd8, causal, striped):
    q, k, v = _qkv(1, S=128, H=2)
    if striped:
        q, k, v = (stripe_sequence(t, N) for t in (q, k, v))
    serial = _runner(hvd8, ring_flash_attention, causal, striped,
                     "serial")(q, k, v)
    overlap = _runner(hvd8, ring_flash_attention, causal, striped,
                      "overlap")(q, k, v)
    for a, b in zip(serial, overlap):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.parametrize(
    "fn",
    [ring_attention,
     # flash bf16 variant ~20s on the tier-1 box: nightly tier
     pytest.param(ring_flash_attention, marks=pytest.mark.slow)],
    ids=["ring", "ring_flash"])
def test_overlap_matches_serial_bf16(hvd8, fn):
    """bf16 inputs ride the same f32 carries in both schedules."""
    q, k, v = _qkv(2, S=128, H=2, dtype=np.float32)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    serial = _runner(hvd8, fn, True, False, "serial")(qb, kb, vb)
    overlap = _runner(hvd8, fn, True, False, "overlap")(qb, kb, vb)
    assert overlap[0].dtype == jnp.bfloat16
    for a, b in zip(serial, overlap):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=2e-2)


def test_invalid_schedule_rejected(hvd8):
    q, k, v = _qkv(3)
    with pytest.raises(ValueError, match="schedule"):
        _runner(hvd8, ring_attention, True, False, "eager")(q, k, v)


def test_contiguous_causal_skip_never_invokes_kernel(hvd8):
    """The acceptance proof for the true-skip arm: count RUNTIME flash
    kernel executions via the ring kernel callback (jax.debug.callback
    fires only inside the branch lax.switch actually runs).  Contiguous
    causal on n shards has sum(my+1) = n(n+1)/2 attended hops; the serial
    schedule runs a (masked, discarded) kernel on every hop = n^2."""
    q, k, v = _qkv(4, S=128, H=2)
    counts = []
    ring_mod.set_ring_kernel_callback(lambda mode: counts.append(mode))
    try:
        def build(schedule):
            def run(q, k, v):
                return ring_flash_attention(q, k, v, axis_name="hvd",
                                            causal=True, schedule=schedule)
            return jax.jit(jax.shard_map(
                run, mesh=hvd8.mesh(), in_specs=(P(None, "hvd"),) * 3,
                out_specs=P(None, "hvd"), check_vma=False))

        jax.block_until_ready(build("overlap")(q, k, v))
        jax.effects_barrier()
        assert len(counts) == N * (N + 1) // 2, len(counts)

        counts.clear()
        jax.block_until_ready(build("serial")(q, k, v))
        jax.effects_barrier()
        assert len(counts) == N * N, len(counts)
    finally:
        ring_mod.set_ring_kernel_callback(None)


def test_striped_single_row_strict_hops_skip(hvd8):
    """S_local == 1 is the one striped case where a strict hop is provably
    empty as a whole — the skip arm must replace the STRICT kernel: only
    owner <= my hops (n(n+1)/2 total) invoke a kernel."""
    q, k, v = _qkv(5, S=N, H=2, D=16)  # one row per shard
    qs, ks, vs = (stripe_sequence(t, N) for t in (q, k, v))
    counts = []
    ring_mod.set_ring_kernel_callback(lambda mode: counts.append(mode))
    try:
        run = jax.jit(jax.shard_map(
            lambda a, b, c: ring_flash_attention(
                a, b, c, axis_name="hvd", causal=True, striped=True),
            mesh=hvd8.mesh(), in_specs=(P(None, "hvd"),) * 3,
            out_specs=P(None, "hvd"), check_vma=False))
        jax.block_until_ready(run(qs, ks, vs))
        jax.effects_barrier()
        assert len(counts) == N * (N + 1) // 2, len(counts)
    finally:
        ring_mod.set_ring_kernel_callback(None)


def test_ring_reduce_double_buffered_product(hvd8):
    """The double-buffered _ring_reduce keeps PRODUCT allreduce exact and
    rank-identical (fold order unchanged, leader canonicalization)."""
    vals = np.asarray([1.5, -2.0, 0.5, 3.0, 1.25, -1.0, 2.0, 0.25],
                      np.float32)
    x = jnp.asarray(vals).reshape(N, 1)

    def f(x):
        return hvd.ops.collective_ops.allreduce(
            x, hvd.Product, axis_name="hvd")

    out = jax.jit(jax.shard_map(
        f, mesh=hvd8.mesh(), in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    arr = np.asarray(out).ravel()
    np.testing.assert_allclose(arr, np.full(N, np.prod(vals)), rtol=1e-6)
    assert len(set(arr.tolist())) == 1  # bitwise-identical on every rank


def test_timeline_records_hop_schedule(hvd8, tmp_path):
    """set_ring_timeline: tracing a ring collective emits one RING_HOP
    event per hop with bytes rotated, mask rule, schedule, and the
    skipped-shard count of the true-skip arm."""
    from horovod_tpu.timeline import Timeline
    path = str(tmp_path / "ring_tl.json")
    tl = Timeline(path)
    ring_mod.set_ring_timeline(tl, "tltest")
    try:
        q, k, v = _qkv(6)
        out = _runner(hvd8, ring_attention, True, False, "overlap")(q, k, v)
        jax.block_until_ready(out)
    finally:
        ring_mod.set_ring_timeline(None)
        tl.close()
    events = [e for e in json.load(open(path))
              if e.get("name", "").startswith("RING_HOP")]
    hops = {e["args"]["hop"]: e["args"] for e in events
            if e["tid"] == "tltest/ring_attention"}
    assert set(hops) == set(range(N))
    B, S, H, D = 2, 64 // N, 4, 16
    for hop, args in hops.items():
        assert args["bytes_rotated"] == 2 * B * S * H * D * 4
        assert args["mask"] == "causal-contiguous"
        assert args["schedule"] == "overlap"
        assert args["skipped_shards"] == (N - hop if hop else 0)


@pytest.mark.integration
@pytest.mark.slow  # ~7s bench smoke
def test_bench_ring_microbench_smoke():
    """bench.py BENCH_MODEL=ring end-to-end on the emulated 8-device CPU
    mesh: one JSON line with the overlapped step time, the serial/overlap
    ratio, the full variant matrix, and per-hop kernel/transfer spans."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", BENCH_MODEL="ring", BENCH_SMOKE="1",
               HVD_TPU_BENCH_TAG="pytestring", HVD_TPU_EMULATE_RANKS="8",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               BENCH_PROBE_BUDGET_S="120", BENCH_PROBE_TIMEOUT_S="60")
    env.pop("HOROVOD_TIMELINE", None)
    try:
        r = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                           env=env, capture_output=True, text=True,
                           timeout=420)
    finally:
        try:  # drop the keyed capture the smoke run persists
            # (_last_good_path keys BENCH_MODEL=ring + BENCH_SMOKE + tag)
            os.remove(os.path.join(repo, "artifacts",
                                   "last_bench_ring_smoke_pytestring.json"))
        except OSError:
            pass
    assert r.returncode == 0, r.stderr[-2000:]
    records = [json.loads(l) for l in r.stdout.splitlines()
               if l.strip().startswith("{")]
    last = records[-1]
    assert last["metric"] == "ring_sp_causal_ms_per_step"
    assert set(last["variants"]) == {
        "contiguous_causal_serial", "contiguous_causal_overlap",
        "striped_causal_overlap", "full_overlap"}
    assert last["per_hop"]["transfer_ms"] >= 0
    assert last["per_hop"]["kernel_ms"] > 0
    assert last["vs_baseline"] > 0
