"""hvdrace corpus: seeded HVD2xx violations + clean fixtures + witness.

Mirrors tests/test_hvdlint.py's contract for the lock-order &
thread-lifecycle analysis (analysis/lockgraph.py): every HVD2xx rule
fires exactly where the corpus plants it, and must NOT fire on the
adjacent clean fixture (re-entrant RLock self-acquisition is not
HVD200; a daemon or stop-path-joined thread is not HVD203).

The acceptance corpus reproduces the PR 3 batcher-lock/metrics-lock
AB/BA deadlock shape; it must be reported as HVD200 by the static pass
AND — exec'd as real code under the ``HVD_SANITIZE=1`` witness
(analysis/witness.py) — caught live as HVD210.
"""

import json
import textwrap
import threading
import time

import pytest

from horovod_tpu.analysis import RULES, witness
from horovod_tpu.analysis.cli import main as cli_main
from horovod_tpu.analysis.lockgraph import analyze_source, analyze_sources


def findings_of(src, **kw):
    return analyze_source(textwrap.dedent(src), path="corpus.py", **kw)


def fired(src, **kw):
    return [(f.rule, f.line) for f in findings_of(src, **kw)
            if not f.suppressed]


# ---------------------------------------------------------------------------
# The PR 3 AB/BA shape: one corpus, two detectors (acceptance criterion).
# ---------------------------------------------------------------------------

# The batcher/metrics deadlock exactly as PR 3 shipped it: the batcher's
# expiry path reaches into the metrics lock while holding the batcher
# lock, and the /metrics render samples queue depth (batcher lock) while
# holding the metrics lock.
AB_BA_CORPUS = """\
import threading


class Metrics:
    def __init__(self, batcher: "Batcher" = None):
        self._lock = threading.Lock()
        self.requests = {}
        self.batcher = batcher

    def count_request(self, outcome):
        with self._lock:
            self.requests[outcome] = self.requests.get(outcome, 0) + 1

    def render(self):
        with self._lock:
            return {"queue_depth": self.batcher.depth()}


class Batcher:
    def __init__(self, metrics: "Metrics"):
        self._lock = threading.Lock()
        self._queue = []
        self.metrics = metrics
        metrics.batcher = self

    def depth(self):
        with self._lock:
            return len(self._queue)

    def pop_expired(self):
        with self._lock:
            expired, self._queue = self._queue, []
            for r in expired:
                self.metrics.count_request("expired")
"""


def test_pr3_ab_ba_shape_is_hvd200_statically():
    findings = [f for f in analyze_source(AB_BA_CORPUS, path="abba.py")
                if not f.suppressed]
    assert [f.rule for f in findings] == ["HVD200"]
    (f,) = findings
    # Both witness paths printed: batcher-then-metrics and the render
    # direction's callback edge (here a direct call so the static pass
    # can close it).
    assert "Batcher._lock" in f.message and "Metrics._lock" in f.message
    assert "path 1" in f.message and "path 2" in f.message


def test_pr3_ab_ba_shape_is_caught_live_by_witness():
    """The same corpus exec'd as real code under the installed witness:
    driving the two paths (single-threaded — no actual deadlock needed)
    must record an HVD210 inversion."""
    was_installed = witness.installed()
    witness.install()
    witness.reset()
    try:
        ns = {}
        exec(compile(AB_BA_CORPUS, "abba_corpus", "exec"), ns)
        metrics = ns["Metrics"]()
        batcher = ns["Batcher"](metrics)
        batcher._queue.append("r1")
        batcher.pop_expired()   # batcher lock -> metrics lock
        metrics.render()        # metrics lock -> batcher lock: inversion
        rules = [f.rule for f in witness.findings()]
        assert rules == ["HVD210"], rules
        (f,) = witness.findings()
        assert "abba_corpus" in f.message or "abba_corpus" in f.path
    finally:
        witness.reset()
        if not was_installed:
            witness.uninstall()


# ---------------------------------------------------------------------------
# HVD200: cycles, self-deadlock, declared orders
# ---------------------------------------------------------------------------

def test_hvd200_non_reentrant_self_reacquire():
    src = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def outer(self):
            with self._lock:
                self.inner()

        def inner(self):
            with self._lock:
                pass
    """
    assert fired(src) == [("HVD200", 9)]


def test_hvd200_reentrant_rlock_is_clean():
    """RLock self-acquisition is re-entrant by contract: NOT a cycle."""
    src = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.RLock()

        def outer(self):
            with self._lock:
                self.inner()

        def inner(self):
            with self._lock:
                pass
    """
    assert fired(src) == []


def test_hvd200_condition_shares_its_locks_identity():
    """Condition(self._lock) IS self._lock: with-cond then with-lock in
    the same class must not self-cycle."""
    src = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)

        def submit(self):
            with self._cond:
                pass

        def depth(self):
            with self._lock:
                return 0
    """
    assert fired(src) == []


def test_hvd200_cross_module_cycle():
    """The lock graph is global: each half of the cycle in its own
    module (the real serve layout)."""
    mod_a = textwrap.dedent("""\
        import threading

        class A:
            def __init__(self, b: "B"):
                self._lock = threading.Lock()
                self.b = b

            def hold_then_call(self):
                with self._lock:
                    self.b.poke()

            def poke(self):
                with self._lock:
                    pass
        """)
    mod_b = textwrap.dedent("""\
        import threading

        class B:
            def __init__(self, a):
                self._lock = threading.Lock()
                self.a = a

            def hold_then_call(self):
                with self._lock:
                    self.a.poke()

            def poke(self):
                with self._lock:
                    pass
        """)
    # B.__init__'s `a` param is unannotated on purpose: resolution comes
    # from an annotated attribute elsewhere — so annotate it here.
    mod_b = mod_b.replace("def __init__(self, a):",
                          "def __init__(self, a: \"A\"):")
    findings = [f for f in analyze_sources([(mod_a, "a.py"), (mod_b, "b.py")])
                if not f.suppressed]
    assert [f.rule for f in findings] == ["HVD200"]
    assert "A._lock" in findings[0].message
    assert "B._lock" in findings[0].message


def test_hvd200_declared_order_inversion_fires_without_opposing_path():
    src = """\
    import threading

    # hvdrace: order=C.lock_a<C.lock_b

    class C:
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def inverted(self):
            with C.lock_b:
                with C.lock_a:
                    pass
    """
    out = fired(src)
    assert out == [("HVD200", 11)]
    (f,) = [f for f in findings_of(src) if not f.suppressed]
    assert "inverts the declared order" in f.message


def test_hvd200_matching_declared_order_is_clean():
    src = """\
    import threading

    # hvdrace: order=C.lock_a<C.lock_b

    class C:
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def ordered(self):
            with C.lock_a:
                with C.lock_b:
                    pass
    """
    assert fired(src) == []


def test_hvd200_contradictory_declarations_are_reported():
    src = """\
    import threading
    # hvdrace: order=x:a<x:b
    # hvdrace: order=x:b<x:a
    a = threading.Lock()
    b = threading.Lock()
    """
    out = fired(src)
    assert ("HVD200", 2) in out
    (f,) = [f for f in findings_of(src) if f.line == 2]
    assert "contradictory" in f.message


def test_hvd200_disable_pragma_on_violating_line():
    src = """\
    import threading

    class C:
        def __init__(self):
            self._lock_a = threading.Lock()
            self._lock_b = threading.Lock()

        def ab(self):
            with self._lock_a:
                with self._lock_b:
                    pass

        def ba(self):
            with self._lock_b:
                with self._lock_a:  # hvdlint: disable=HVD200
                    pass
    """
    findings = findings_of(src)
    assert [(f.rule, f.suppressed) for f in findings] == [("HVD200", True)]


# ---------------------------------------------------------------------------
# HVD201: blocking call while holding a lock
# ---------------------------------------------------------------------------

def test_hvd201_sleep_kv_subprocess_join_under_lock():
    src = """\
    import subprocess
    import threading
    import time

    class C:
        def __init__(self, kv_client):
            self._lock = threading.Lock()
            self.kv_client = kv_client
            self._thread = threading.Thread(target=print, daemon=True)

        def bad(self):
            with self._lock:
                time.sleep(1)
                self.kv_client.scan("preempt")
                subprocess.run(["true"])
                self._thread.join()
    """
    assert fired(src) == [("HVD201", 13), ("HVD201", 14),
                          ("HVD201", 15), ("HVD201", 16)]


def test_hvd201_jitted_call_under_lock():
    src = """\
    import threading
    import jax

    @jax.jit
    def decode_step(x):
        return x + 1

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def bad(self, x):
            with self._lock:
                return decode_step(x)
    """
    assert fired(src) == [("HVD201", 14)]


def test_hvd201_with_nested_in_try_and_loop_still_tracked():
    """Acquisitions inside if/for/try bodies must register (the walker
    once only scanned calls through compound statements) — the batcher's
    own `with self._cond:` sits inside a try."""
    src = """\
    import threading
    import time

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def m(self, flag):
            try:
                if flag:
                    with self._lock:
                        time.sleep(1)
            finally:
                pass

        def loop(self):
            for _ in range(3):
                with self._lock:
                    time.sleep(2)
    """
    assert fired(src) == [("HVD201", 12), ("HVD201", 19)]


def test_hvd202_finally_after_with_is_not_under_the_lock():
    """The fixed batcher shape: callback fired in a finally AFTER the
    with-block released — must stay clean."""
    src = """\
    import threading

    class Batcher:
        def __init__(self, on_shed):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self._on_shed = on_shed

        def get_admission(self):
            expired = []
            try:
                with self._cond:
                    expired.append(1)
            finally:
                for r in expired:
                    self._on_shed(r, "expired")
    """
    assert fired(src) == []


def test_hvd201_clean_blocking_outside_lock():
    src = """\
    import threading
    import time

    class C:
        def __init__(self, kv_client):
            self._lock = threading.Lock()
            self.kv_client = kv_client

        def good(self):
            with self._lock:
                snapshot = 1
            time.sleep(0.01)
            self.kv_client.scan("preempt")
            return snapshot
    """
    assert fired(src) == []


def test_hvd201_dict_get_named_kv_is_not_transport():
    """kv_stats.get(...) is a dict read, not a round-trip (the dogfood
    false positive that narrowed the heuristic)."""
    src = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.kv_stats = {}

        def snapshot(self):
            with self._lock:
                return self.kv_stats.get("used", 0)
    """
    assert fired(src) == []


# ---------------------------------------------------------------------------
# HVD202: callback under a lock
# ---------------------------------------------------------------------------

def test_hvd202_on_shed_callback_under_lock():
    src = """\
    import threading

    class Batcher:
        def __init__(self, on_shed):
            self._lock = threading.Lock()
            self._on_shed = on_shed

        def pop_expired(self):
            with self._lock:
                self._on_shed(None, "expired")
    """
    assert fired(src) == [("HVD202", 10)]


def test_hvd202_registered_fn_container_under_lock():
    src = """\
    import threading

    class Metrics:
        def __init__(self):
            self._lock = threading.Lock()
            self._queue_depth_fns = {}

        def render(self):
            with self._lock:
                return {k: fn() for k, fn in
                        self._queue_depth_fns.items()}

        def render2(self):
            with self._lock:
                return self._queue_depth_fns["a"]()
    """
    assert ("HVD202", 15) in fired(src)


def test_hvd202_module_level_resolvable_callee_is_exempt():
    """A module-level function holding a module-level lock calling an
    in-module helper whose NAME merely matches the callback pattern is
    resolvable, not arbitrary (review regression: the exemption only
    applied inside classes)."""
    src = """\
    import threading

    _LOCK = threading.Lock()

    def flush_hook():
        return 1

    def flush():
        with _LOCK:
            flush_hook()
    """
    assert fired(src) == []


def test_hvd202_clean_callback_fired_after_release():
    src = """\
    import threading

    class Batcher:
        def __init__(self, on_shed):
            self._lock = threading.Lock()
            self._on_shed = on_shed

        def pop_expired(self):
            expired = []
            with self._lock:
                expired.append(1)
            for r in expired:
                self._on_shed(r, "expired")
    """
    assert fired(src) == []


# ---------------------------------------------------------------------------
# HVD203: thread lifecycle
# ---------------------------------------------------------------------------

def test_hvd203_unjoined_non_daemon_attr_thread():
    src = """\
    import threading

    class Srv:
        def start(self):
            self._thread = threading.Thread(target=print)
            self._thread.start()
    """
    assert fired(src) == [("HVD203", 5)]


def test_hvd203_fire_and_forget():
    src = """\
    import threading

    def go():
        threading.Thread(target=print).start()
    """
    assert fired(src) == [("HVD203", 4)]


def test_hvd203_daemon_thread_is_clean():
    src = """\
    import threading

    def go():
        threading.Thread(target=print, daemon=True).start()
    """
    assert fired(src) == []


def test_hvd203_joined_on_stop_path_is_clean():
    src = """\
    import threading

    class Srv:
        def start(self):
            self._thread = threading.Thread(target=print)
            self._thread.start()

        def stop(self):
            self._thread.join(timeout=5)
    """
    assert fired(src) == []


def test_hvd203_other_classes_join_does_not_suppress():
    """A sibling class joining its own same-named `_thread` attr must not
    hide this class's leaked thread (review regression: joined_attrs was
    checked module-wide)."""
    src = """\
    import threading

    class Leaky:
        def start(self):
            self._thread = threading.Thread(target=print)
            self._thread.start()

    class Clean:
        def start(self):
            self._thread = threading.Thread(target=print)
            self._thread.start()

        def stop(self):
            self._thread.join(timeout=5)
    """
    assert fired(src) == [("HVD203", 5)]


def test_hvd203_local_join_and_daemon_attr_are_clean():
    src = """\
    import threading

    def joined():
        t = threading.Thread(target=print)
        t.start()
        t.join()

    def daemonized_after():
        t = threading.Thread(target=print)
        t.daemon = True
        t.start()

    def pool():
        threads = [threading.Thread(target=print),
                   threading.Thread(target=print)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    """
    assert fired(src) == []


# ---------------------------------------------------------------------------
# Witness runtime unit coverage (beyond the AB/BA acceptance test)
# ---------------------------------------------------------------------------

@pytest.fixture()
def installed_witness():
    was = witness.installed()
    witness.install()
    witness.reset()
    yield witness
    witness.reset()
    if not was:
        witness.uninstall()


def test_witness_consistent_order_is_clean(installed_witness):
    # Separate lines: same-line construction would share one witness
    # class and record no edges at all (a vacuous pass).
    a = threading.Lock()
    b = threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    # The A-before-B edge was really observed — the clean result is not
    # for want of bookkeeping.
    assert any(k[1] != k[0] for k in witness.order_graph())
    assert witness.findings() == []


def test_witness_rlock_reentry_is_clean(installed_witness):
    r = threading.RLock()
    with r:
        with r:
            pass
    assert witness.findings() == []


def test_witness_inversion_across_threads(installed_witness):
    # Separate lines: witness identity is the construction SITE (two
    # locks born on one line would share a witness class).
    a = threading.Lock()
    b = threading.Lock()

    def path_ab():
        with a:
            with b:
                pass

    def path_ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=path_ab, daemon=True)
    t1.start()
    t1.join(5)
    t2 = threading.Thread(target=path_ba, daemon=True)
    t2.start()
    t2.join(5)
    assert [f.rule for f in witness.findings()] == ["HVD210"]
    # Deduped: driving the inversion again reports nothing new.
    path_ba()
    assert len(witness.findings()) == 1


def test_witness_naked_condition_wait_holding_second_lock(
        installed_witness):
    other = threading.Lock()
    cond = threading.Condition()

    def waiter():
        with other:
            with cond:
                cond.wait()   # timeout-less + second lock held: HVD211

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = 50
    while deadline and not witness.findings():
        threading.Event().wait(0.05)
        deadline -= 1
    with cond:
        cond.notify_all()
    t.join(5)
    assert [f.rule for f in witness.findings()] == ["HVD211"]


def test_witness_thread_start_under_lock_is_not_a_naked_wait(
        installed_witness):
    """Thread.start() blocks on its internal timeout-less _started.wait;
    starting a thread while holding a lock (the negotiator's
    _start_flusher shape) must NOT be HVD211 — the started event is set
    promptly by construction (review regression: this fired on real repo
    code under HVD_SANITIZE=1).  A USER-level naked Event.wait under a
    lock stays a finding."""
    guard = threading.Lock()
    with guard:
        t = threading.Thread(target=lambda: None, daemon=True)
        t.start()
    t.join(5)
    assert witness.findings() == []
    # Contrast: user code naked-waiting an Event while holding the lock.
    ev = threading.Event()
    waiter_err = []

    def waiter():
        try:
            with guard:
                ev.wait()
        except Exception as e:  # pragma: no cover - diagnosis aid
            waiter_err.append(e)

    t2 = threading.Thread(target=waiter, daemon=True)
    t2.start()
    deadline = 100
    while deadline and not witness.findings():
        time.sleep(0.02)
        deadline -= 1
    ev.set()
    t2.join(5)
    assert not waiter_err
    assert [f.rule for f in witness.findings()] == ["HVD211"]


def test_witness_raise_mode_releases_the_violating_acquisition(
        installed_witness):
    """HVD_RACE_RAISE debug mode: the LockOrderViolation raised from
    __enter__ must not leave the just-acquired raw lock held (review
    regression: a leaked lock turned the diagnosis into a wedge)."""
    from horovod_tpu.analysis.witness import (LockOrderViolation, _state)
    _state.raise_on_violation = True
    try:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with pytest.raises(LockOrderViolation):
            with b:
                with a:
                    pass
        assert not a.locked() and not b.locked()
        with a:  # must not deadlock on the leaked lock
            pass
    finally:
        _state.raise_on_violation = False


def test_witness_bounded_wait_is_clean(installed_witness):
    other = threading.Lock()
    cond = threading.Condition()
    with other:
        with cond:
            cond.wait(timeout=0.01)
    assert witness.findings() == []


def test_witness_declare_order_preseeds_canonical_direction(
        installed_witness):
    witness.declare_order("site:a", "site:b")
    assert ("site:a", "site:b") in witness.order_graph()


def test_witness_findings_surface_in_reports_and_timeline(
        installed_witness, monkeypatch):
    """Findings publish to core.analysis_reports() (a WitnessReport) and
    emit WITNESS/<rule> timeline instants like the faultline firings."""
    from horovod_tpu import core as _core
    from horovod_tpu.analysis.witness import WitnessReport

    events = []

    class _TL:
        def witness_event(self, rule, path, line, thread):
            events.append((rule, path, line, thread))

    monkeypatch.setattr(_core._state, "timeline", _TL())
    monkeypatch.setattr(_core._state, "analysis_reports", [])
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    reports = [r for r in _core.analysis_reports()
               if isinstance(r, WitnessReport)]
    assert len(reports) == 1 and not reports[0].ok()
    assert [f.rule for f in reports[0].findings] == ["HVD210"]
    assert [e[0] for e in events] == ["HVD210"]
    assert events[0][3] == threading.current_thread().name


def test_witness_events_and_queues_work_while_installed(installed_witness):
    import queue
    e = threading.Event()
    e.set()
    assert e.wait(0.1)
    q = queue.Queue()
    q.put("x")
    assert q.get(timeout=1) == "x"
    assert witness.findings() == []


# ---------------------------------------------------------------------------
# CLI --race contract (exit codes, JSON, catalogue)
# ---------------------------------------------------------------------------

@pytest.fixture()
def race_corpus_dir(tmp_path):
    (tmp_path / "dirty.py").write_text(textwrap.dedent("""\
        import threading

        class Srv:
            def start(self):
                self._thread = threading.Thread(target=print)
                self._thread.start()
        """))
    (tmp_path / "clean.py").write_text(textwrap.dedent("""\
        import threading

        def go():
            threading.Thread(target=print, daemon=True).start()
        """))
    return tmp_path


def test_cli_race_exit_codes_and_text(race_corpus_dir, capsys):
    rc = cli_main(["--race", str(race_corpus_dir)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD203" in out and "dirty.py" in out
    rc = cli_main(["--race", str(race_corpus_dir / "clean.py")])
    assert rc == 0


def test_cli_race_json(race_corpus_dir, capsys):
    rc = cli_main(["--race", str(race_corpus_dir), "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["summary"]["by_rule"] == {"HVD203": 1}
    (f,) = payload["findings"]
    assert f["rule"] == "HVD203" and f["source"] == "race"


def test_cli_race_syntax_error_is_hvd000_not_crash(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    rc = cli_main(["--race", str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD000" in out


def test_cli_race_missing_path_is_a_finding(capsys):
    rc = cli_main(["--race", "/nonexistent/hvdrace/path"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD000" in out and "does not exist" in out


def test_cli_race_select_ignore(race_corpus_dir, capsys):
    rc = cli_main(["--race", str(race_corpus_dir), "--ignore", "HVD203"])
    capsys.readouterr()
    assert rc == 0
    rc = cli_main(["--race", str(race_corpus_dir), "--select", "HVD201"])
    capsys.readouterr()
    assert rc == 0


def test_hvd2xx_catalogue_metadata():
    for rule_id in ("HVD200", "HVD201", "HVD202", "HVD203",
                    "HVD210", "HVD211"):
        assert rule_id in RULES
    src = """\
    import threading

    def go():
        threading.Thread(target=print).start()
    """
    (f,) = findings_of(src)
    assert f.severity == RULES["HVD203"].severity
    assert f.fix_hint == RULES["HVD203"].fix_hint
    assert f.source == "race"
