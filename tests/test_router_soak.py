"""ISSUE 18 soak: hvdroute in front of a real 4-endpoint fleet under a
kill + roll storm — the tentpole's acceptance run.

* zero lost requests: every session request answers 200 across an
  endpoint's HTTP listener dying mid-storm (plus a ``kill-rank`` at
  ``router.forward``) and a live ``registry.roll`` on another endpoint,
  and every answer is bit-identical to the single-served reference;
* affinity: repeat sessions keep landing on the endpoint that already
  served them — hit rate stays far above the uniform-routing floor even
  though one endpoint's sessions were forcibly remapped;
* hedging: with a ``slow-route`` fault stalling one endpoint, the
  hedged router's p99 beats (or ties) the unhedged router's;
* drain: ``python -m horovod_tpu.serve.router`` under SIGTERM drains
  and exits 0 — the front-door runbook contract.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

import horovod_tpu.faultline as fl
from horovod_tpu.models import create_mlp
from horovod_tpu.serve import (MLPAdapter, ModelRegistry, Router,
                               RouterConfig, RouterServer, ServeMetrics,
                               ServeServer, build_replicas)

pytestmark = pytest.mark.slow

VOCAB = 31
TOKS = 6


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    fl.uninstall()
    yield
    fl.uninstall()


def _params(seed=3):
    mlp = create_mlp(features=(16, VOCAB))
    return mlp, mlp.init(jax.random.PRNGKey(seed),
                         np.zeros((1, VOCAB), np.float32))["params"]


def _mlp_chain(adapter, prompt, n):
    seq = []
    tok = prompt[-1]
    for _ in range(n):
        tok = int(adapter._apply(np.asarray([tok], np.int32))[0])
        seq.append(tok)
    return seq


def _fleet(n, mlp, params):
    """n single-replica serve endpoints sharing the same weights (so
    every endpoint answers every prompt identically — the router may
    land a session anywhere without changing its output)."""
    servers, endpoints = [], []
    for _ in range(n):
        adapter = MLPAdapter(mlp, params, vocab_size=VOCAB, max_len=128)
        sched = build_replicas(lambda: adapter, num_replicas=1,
                               metrics=ServeMetrics())
        srv = ServeServer(sched)
        port = srv.start(port=0, host="127.0.0.1")
        servers.append(srv)
        endpoints.append(f"127.0.0.1:{port}")
    return servers, endpoints


def _post(port, payload, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Timeout-S": "30"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_router_soak_zero_lost_under_kill_and_roll():
    mlp, params = _params()
    servers, endpoints = _fleet(4, mlp, params)
    config = RouterConfig(retry_base_s=0.005, retry_cap_s=0.05,
                          eject_failures=2, probe_s=0.2)
    router = Router(endpoints, config=config)
    rsrv = RouterServer(router)
    rport = rsrv.start(port=0, host="127.0.0.1")

    rng = np.random.RandomState(0)
    sessions = [rng.randint(0, VOCAB, size=(int(rng.randint(6, 14)),)
                            ).tolist() for _ in range(10)]
    results = []  # (session, status, tokens)
    results_lock = threading.Lock()

    def storm(reps, workers=4):
        work = [(i, p) for _ in range(reps)
                for i, p in enumerate(sessions)]
        chunk = (len(work) + workers - 1) // workers

        def run(items):
            for i, p in items:
                st, body = _post(rport,
                                 {"tokens": p, "max_new_tokens": TOKS})
                with results_lock:
                    results.append((i, st, tuple(body.get("tokens", ()))))

        threads = [threading.Thread(
            target=run, args=(work[k * chunk:(k + 1) * chunk],),
            daemon=True) for k in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "storm worker wedged"

    try:
        # Phase A: clean fleet — sessions pin to their affinity targets.
        storm(reps=3)
        # Chaos: kill one endpoint's HTTP LISTENER only (its engine
        # lives on, as a real preemption looks from the router's seat),
        # declare the loss at router.forward too, and roll another
        # endpoint's weights live mid-storm.
        victim = router._ring.lookup(router.affinity_key(sessions[0]))[0]
        victim_srv = servers[endpoints.index(victim)]
        victim_srv.httpd.shutdown()
        victim_srv.httpd.server_close()
        fl.install(fl.parse_plan(f"kill-rank:{victim}@0*1/router.forward"))
        roll_srv = next(s for e, s in zip(endpoints, servers)
                        if e != victim)
        reg = ModelRegistry(roll_srv.scheduler)
        reg.adopt("default")
        roller = threading.Thread(
            target=lambda: reg.roll(
                "default",
                adapter=MLPAdapter(mlp, params, vocab_size=VOCAB,
                                   max_len=128)),
            daemon=True)
        roller.start()
        # Phase B: the same sessions through the degraded fleet.
        storm(reps=3)
        roller.join(timeout=60)
        assert not roller.is_alive(), "roll wedged mid-storm"
    finally:
        fl.uninstall()
        rsrv.stop()
        for srv in servers:
            try:
                srv.stop()
            except Exception:
                pass  # the victim's listener is already down

    # Zero lost: every request across both phases answered 200 with the
    # single-served reference output, bit-identical.
    assert len(results) == 10 * 6
    assert all(st == 200 for _, st, _ in results)
    ref_adapter = MLPAdapter(mlp, params, vocab_size=VOCAB, max_len=128)
    for i, p in enumerate(sessions):
        expect = tuple(_mlp_chain(ref_adapter, p, TOKS))
        got = {out for j, _, out in results if j == i}
        assert got == {expect}, f"session {i} diverged: {got} != {expect}"

    # Affinity: at most the victim's sessions were remapped, so the hit
    # rate stays far above the 1/4 uniform-routing floor.
    snap = router.metrics.snapshot()
    assert snap["affinity"]["hit_rate"] >= 0.5
    assert snap["ejections"] >= 1  # the kill was observed and acted on
    assert snap["requests"]["ok"] == 60
    assert snap["requests"].get("error", 0) == 0


def test_router_soak_hedged_p99_beats_unhedged():
    mlp, params = _params()
    servers, endpoints = _fleet(2, mlp, params)
    stall = 0.25
    lat = {}
    try:
        probe = Router(endpoints, config=RouterConfig())
        prompts = []
        s = 0
        while len(prompts) < 6 and s < 4096:
            p = [(13 * s + j) % VOCAB for j in range(10)]
            if probe._ring.lookup(probe.affinity_key(p))[0] == endpoints[0]:
                prompts.append(p)
            s += 1
        assert len(prompts) == 6
        for mode, hedge_s in (("unhedged", 0.0), ("hedged", 0.03)):
            router = Router(endpoints,
                            config=RouterConfig(hedge_s=hedge_s))
            fl.install(fl.parse_plan(
                f"slow-route:{endpoints[0]}@0*100000~{stall}"
                f"/router.forward"))
            samples = []
            try:
                for p in prompts:
                    t0 = time.perf_counter()
                    status, _, _ = router.handle(
                        json.dumps({"tokens": p,
                                    "max_new_tokens": TOKS}).encode(), {})
                    samples.append(time.perf_counter() - t0)
                    assert status == 200
            finally:
                fl.uninstall()
            lat[mode] = sorted(samples)[-1]  # p99 == max at n=6
    finally:
        for srv in servers:
            srv.stop()
    # Every prompt's affinity target is the stalled endpoint: unhedged
    # requests eat the stall, hedged ones race the second endpoint.
    assert lat["unhedged"] >= stall
    assert lat["hedged"] <= lat["unhedged"]


def test_hvdroute_sigterm_drains_and_exits_zero():
    env = dict(os.environ, JAX_PLATFORMS="cpu", HVD_ROUTE_DRAIN_S="10")
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.serve.router",
         "--endpoints", "127.0.0.1:9", "--port", "0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        banner = {}

        def read_banner():
            banner["line"] = proc.stdout.readline()

        t = threading.Thread(target=read_banner, daemon=True)
        t.start()
        t.join(timeout=60)
        assert banner.get("line", "").startswith(
            "hvdroute: listening on :"), banner
        port = int(banner["line"].split(":")[2].split()[0])
        # The front door is actually serving before the signal.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert resp.status == 200
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        # Drain-then-exit-0: the runbook contract (no 5xx, no crash).
        assert rc == 0, proc.stderr.read()[-2000:]
    finally:
        proc.kill()
