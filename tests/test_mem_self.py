"""Self-hvdmem regression gate: the repo must stay hvdmem-clean.

The analog of tests/test_lint_self.py / test_race_self.py for the HBM
donation analysis (analysis/memplan.py): runs ``--mem`` over
``horovod_tpu/`` + ``examples/`` in-process and fails on ANY unsuppressed
HVD3xx finding — a new donated-then-used cache read (the PR 4 hazard
class) or an undonated functionally-updated jit arg fails tier-1 before
it can OOM or crash a serving fleet.

To silence a deliberate pattern, add ``# hvdlint: disable=HVD30x`` on
the flagged line WITH a reasoned comment (docs/static_analysis.md).
"""

import os

from horovod_tpu.analysis import mem_paths, unsuppressed
from horovod_tpu.analysis.cli import main as cli_main

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PATHS = [os.path.join(_REPO, "horovod_tpu"),
          os.path.join(_REPO, "examples")]


def test_repo_is_hvdmem_clean():
    findings = mem_paths(_PATHS)
    active = unsuppressed(findings)
    assert not active, (
        "hvdmem found HBM donation hazards — fix them (rebind the "
        "donated name / add donate_argnums) or suppress each with a "
        "reasoned '# hvdlint: disable=...' comment:\n"
        + "\n".join(f.format() for f in active))


def test_mem_suppressions_are_auditable():
    """Every suppressed hvdmem finding still surfaces with
    suppressed=True — the audit trail the dogfooding satellite
    requires."""
    for f in mem_paths(_PATHS):
        assert f.suppressed, f.format()


def test_mem_walk_covers_the_donating_tree():
    """Guard the gate itself: the walk must actually reach the donation-
    heavy subsystems — zero findings would mean nothing if the walker
    silently skipped the serve engine (five donated jit programs) or the
    analyzer's own modules."""
    from horovod_tpu.analysis.linter import iter_python_files
    files = iter_python_files(_PATHS)
    assert len(files) > 50
    for mod in (os.path.join("serve", "engine.py"),
                os.path.join("serve", "sampling.py"),
                os.path.join("serve", "controller.py"),
                os.path.join("serve", "tenancy.py"),
                os.path.join("serve", "registry.py"),
                os.path.join("serve", "tiering.py"),
                os.path.join("serve", "seqpar.py"),
                os.path.join("parallel", "__init__.py"),
                os.path.join("analysis", "memplan.py"),
                os.path.join("analysis", "shardplan.py")):
        assert any(f.endswith(mod) for f in files), f"{mod} not analyzed"
    assert not any("__pycache__" in f for f in files)


def test_mem_dogfood_cli_exits_zero(capsys):
    """The acceptance command, through the registry dispatch:
    python -m horovod_tpu.analysis --mem horovod_tpu examples."""
    rc = cli_main(["--mem"] + _PATHS)
    capsys.readouterr()
    assert rc == 0
