"""Elastic subsystem tests.

Mirrors the reference's split (SURVEY.md §4): driver logic tested
single-process with scripted discovery and simulated worker exits
(test/single/test_elastic_driver.py), state save/restore without a cluster
(test/single/test_torch_elastic.py), and the retry loop with synthetic
exceptions (common/elastic.py contract).
"""

import json
import os
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Serialize with the other subprocess-world e2e files (conftest
# pytest_collection_modifyitems): overlapping multi-process worlds on one
# host core cascade spurious stall timeouts.
pytestmark = pytest.mark.xdist_group("heavy_e2e")

import subprocess as _subprocess  # noqa: E402


def run_world(cmd, *, timeout, env=None, tag="world"):
    """subprocess.run wrapper that DUMPS the world's full output to /tmp
    on a timeout — the assertion repr truncates it, which made wedged
    elastic worlds undiagnosable."""
    try:
        return _subprocess.run(cmd, cwd=REPO, capture_output=True,
                               text=True, timeout=timeout, env=env)
    except _subprocess.TimeoutExpired as e:
        dump = f"/tmp/hvd_world_timeout_{tag}_{os.getpid()}.log"
        with open(dump, "w") as f:
            for name, data in (("STDOUT", e.stdout), ("STDERR", e.stderr)):
                f.write(f"==== {name} ====\n")
                if data:
                    f.write(data.decode("utf-8", "replace")
                            if isinstance(data, bytes) else data)
        e.args = (*e.args[:2], e.stdout, e.stderr)
        raise _subprocess.TimeoutExpired(
            e.cmd, e.timeout, output=f"full output dumped to {dump}")

import horovod_tpu as hvd
from horovod_tpu import elastic as E
from horovod_tpu.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from horovod_tpu.runner.http_server import RendezvousServer

N = 8


# -- state objects -----------------------------------------------------------

def test_object_state_save_restore(hvd8):
    state = E.ObjectState(epoch=1, batch=10)
    state.epoch = 5
    state.batch = 99
    state.restore()
    assert state.epoch == 1 and state.batch == 10
    state.epoch = 7
    state.save()
    state.epoch = 0
    state.restore()
    assert state.epoch == 7


def test_tpu_state_arrays_and_objects(hvd8):
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = E.TpuState(params=params, epoch=0)
    state.params = {"w": state.params["w"] * 3}
    state.epoch = 2
    state.restore()  # back to the initial commit
    np.testing.assert_allclose(np.asarray(state.params["w"]), np.ones(4))
    assert state.epoch == 0
    state.params = {"w": state.params["w"] * 5}
    state.epoch = 3
    state.commit()
    state.params = {"w": state.params["w"] * 100}
    state.restore()
    np.testing.assert_allclose(np.asarray(state.params["w"]), 5 * np.ones(4))
    assert state.epoch == 3
    state.sync()  # emulated: broadcast path exercised, values unchanged
    np.testing.assert_allclose(np.asarray(state.params["w"]), 5 * np.ones(4))


def test_state_reset_callbacks(hvd8):
    calls = []
    state = E.ObjectState(x=1)
    state.register_reset_callbacks([lambda: calls.append("a"),
                                    lambda: calls.append("b")])
    state.on_reset()
    assert calls == ["a", "b"]


def test_check_host_updates_raises(hvd8):
    state = E.ObjectState(x=1)
    state._host_messages = []
    state.on_hosts_updated({"h1": 2}, 1)
    with pytest.raises(HostsUpdatedInterrupt) as ei:
        state.commit()
    assert not ei.value.skip_sync  # removal requires sync
    state.on_hosts_updated({"h1": 2, "h2": 2}, 2)
    with pytest.raises(HostsUpdatedInterrupt) as ei:
        state.check_host_updates()
    assert ei.value.skip_sync  # pure scale-up


# -- retry loop (common/elastic.py:151) ---------------------------------------

def test_elastic_run_retries_on_internal_error(hvd8):
    events = []

    class FakeState(E.State):
        def __init__(self):
            super().__init__()
            self.restored = 0

        def save(self): events.append("save")
        def restore(self): self.restored += 1; events.append("restore")
        def sync(self): events.append("sync")

    state = FakeState()
    attempts = []

    @E.run
    def train(st):
        attempts.append(1)
        if len(attempts) < 3:
            raise HorovodInternalError("collective failed")
        return "done"

    assert train(state) == "done"
    assert len(attempts) == 3
    assert state.restored == 2
    assert events.count("sync") == 3  # sync after every restore + initial


def test_elastic_run_hosts_updated_skips_sync_on_scaleup(hvd8):
    syncs = []

    class FakeState(E.State):
        def save(self): pass
        def restore(self): pass
        def sync(self): syncs.append(1)

    state = FakeState()
    attempts = []

    @E.run
    def train(st):
        attempts.append(1)
        if len(attempts) == 1:
            raise HostsUpdatedInterrupt(skip_sync=True)
        return 42

    assert train(state) == 42
    assert len(syncs) == 1  # only the initial sync; scale-up skipped one


# -- discovery / blacklist ----------------------------------------------------

def test_discovery_script_parsing(tmp_path):
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho h1:4\necho h2\n")
    script.chmod(0o755)
    d = E.HostDiscoveryScript(str(script), slots=2)
    assert d.find_available_hosts_and_slots() == {"h1": 4, "h2": 2}


def test_blacklist_cooldown():
    from horovod_tpu.elastic.discovery import Blacklist
    bl = Blacklist(cooldown_range=(0.05, 0.2))
    bl.blacklist("h1")
    assert bl.is_blacklisted("h1")
    time.sleep(0.3)
    assert not bl.is_blacklisted("h1")  # cooled down
    bl2 = Blacklist(None)
    bl2.blacklist("h2")
    time.sleep(0.05)
    assert bl2.is_blacklisted("h2")  # permanent without range


def test_host_manager_update_results():
    disc = E.FixedHostDiscovery({"h1": 2})
    hm = E.HostManager(disc)
    assert hm.update_available_hosts() == 2  # initial add
    assert hm.update_available_hosts() == 0  # no change
    disc._hosts["h2"] = 2
    assert hm.update_available_hosts() == 2  # scale-up
    del disc._hosts["h1"]
    assert hm.update_available_hosts() == 1  # removal


# -- driver (test_elastic_driver.py analog) -----------------------------------

class RecordingWorkers:
    """Simulated workers: run until told to exit with a given code."""

    def __init__(self):
        self.launched = []
        self.exit_codes = {}
        self.events = {}

    def fn(self, slot, terminate_event, world_version=0):
        key = (slot.hostname, slot.local_rank)
        self.launched.append((slot.rank, key))
        ev = threading.Event()
        self.events[key] = ev
        while not ev.is_set() and not terminate_event.is_set():
            time.sleep(0.01)
        return self.exit_codes.get(key, 0)

    def finish(self, host, slot, code=0):
        self.exit_codes[(host, slot)] = code
        self.events[(host, slot)].set()


def _make_driver(hosts, min_np, max_np, **kwargs):
    rendezvous = RendezvousServer()
    rendezvous.start()
    disc = E.FixedHostDiscovery(hosts)
    driver = E.ElasticDriver(rendezvous, disc, min_np, max_np, **kwargs)
    return driver, rendezvous, disc


def test_driver_initial_world_and_rendezvous():
    driver, rdv, disc = _make_driver({"hA": 2, "hB": 2}, 4, 4)
    workers = RecordingWorkers()
    driver.start(workers.fn)
    try:
        time.sleep(0.1)
        assert len(workers.launched) == 4
        rec = json.loads(rdv.get("rendezvous", "rank/0"))
        assert rec["size"] == 4 and rec["version"] == 1
        assert rdv.get("rendezvous", "size") == b"4"
        # graceful completion
        for host in ("hA", "hB"):
            for s in (0, 1):
                workers.finish(host, s, 0)
        driver.join()
        assert driver.error_message is None
        states = driver.registry.last_rank_states()
        assert all(v == "SUCCESS" for v in states.values())
    finally:
        driver.stop()
        rdv.stop()


def test_driver_failure_blacklists_and_reassigns():
    driver, rdv, disc = _make_driver({"hA": 2, "hB": 2}, 2, 4,
                                     cooldown_range=None)
    workers = RecordingWorkers()
    driver.start(workers.fn)
    try:
        time.sleep(0.1)
        v1 = driver.world_version
        # hB's worker 0 fails -> host blacklisted -> resume with hA only
        workers.finish("hB", 0, 1)
        deadline = time.time() + 5
        while driver.world_version == v1 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.world_version > v1
        assert driver.host_manager.blacklist.is_blacklisted("hB")
        assignments = driver.current_assignments()
        assert all(s.hostname == "hA" for s in assignments)
        assert len(assignments) == 2  # shrank to hA's slots
        # survivors keep their (host, local_rank) slots
        ranks = {(s.hostname, s.local_rank): s.rank for s in assignments}
        assert ("hA", 0) in ranks and ("hA", 1) in ranks
    finally:
        driver.stop()
        rdv.stop()


def test_driver_reset_limit_stops():
    driver, rdv, disc = _make_driver({"hA": 1, "hB": 1, "hC": 1}, 1, 3,
                                     reset_limit=1, cooldown_range=None)
    workers = RecordingWorkers()
    driver.start(workers.fn)
    try:
        time.sleep(0.1)
        workers.finish("hA", 0, 1)  # failure 1 -> reset 1 (at limit)
        time.sleep(0.3)
        workers.finish("hB", 0, 1)  # failure 2 -> exceeds reset limit
        deadline = time.time() + 5
        while driver.error_message is None and time.time() < deadline:
            time.sleep(0.05)
        assert driver.error_message is not None
        assert "Reset limit" in driver.error_message
    finally:
        driver.stop()
        rdv.stop()


def test_refresh_world_fails_fast_when_rendezvous_dead(monkeypatch):
    """A dead launcher (KV port refusing connections) must surface as
    RendezvousUnreachableError within HVD_TPU_RENDEZVOUS_DEAD_S, not poll
    out the full HOROVOD_ELASTIC_TIMEOUT (the round-2 leaked-worker bug:
    orphans survived the launcher by 20+ minutes)."""
    import socket as _socket
    from horovod_tpu import config as _cfg
    from horovod_tpu.exceptions import RendezvousUnreachableError
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    free_port = s.getsockname()[1]
    s.close()  # nothing listens on free_port now
    monkeypatch.setenv(_cfg.HOROVOD_RENDEZVOUS_ADDR, "127.0.0.1")
    monkeypatch.setenv(_cfg.HOROVOD_RENDEZVOUS_PORT, str(free_port))
    monkeypatch.setenv(_cfg.HOROVOD_ELASTIC_TIMEOUT, "120")
    monkeypatch.setenv("HVD_TPU_RENDEZVOUS_DEAD_S", "1")
    t0 = time.time()
    with pytest.raises(RendezvousUnreachableError):
        E._refresh_world_from_rendezvous()
    assert time.time() - t0 < 30  # fast-fail, nowhere near 120 s


def test_init_barrier_fails_fast_when_rendezvous_dead(monkeypatch):
    """Same dead-launcher fast-fail on the pre-init KV barrier path."""
    import socket as _socket
    from horovod_tpu import config as _cfg
    from horovod_tpu.exceptions import RendezvousUnreachableError
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    free_port = s.getsockname()[1]
    s.close()
    monkeypatch.setenv(_cfg.HOROVOD_RENDEZVOUS_ADDR, "127.0.0.1")
    monkeypatch.setenv(_cfg.HOROVOD_RENDEZVOUS_PORT, str(free_port))
    monkeypatch.setenv(_cfg.HOROVOD_ELASTIC_TIMEOUT, "120")
    monkeypatch.setenv("HVD_TPU_RENDEZVOUS_DEAD_S", "1")
    monkeypatch.setenv("HOROVOD_ELASTIC", "1")
    monkeypatch.setenv(_cfg.HOROVOD_RANK, "0")
    monkeypatch.setenv(_cfg.HOROVOD_SIZE, "2")  # >1 so the barrier polls
    t0 = time.time()
    with pytest.raises(RendezvousUnreachableError):
        E._await_world_at_init_barrier()
    assert time.time() - t0 < 30


def test_driver_waits_for_min_slots_timeout():
    driver, rdv, disc = _make_driver({}, 2, 2, timeout=0.5)
    with pytest.raises(RuntimeError, match="Timed out waiting"):
        driver.start(lambda s, e, v: 0)
    rdv.stop()


def test_driver_scale_up_bumps_version():
    driver, rdv, disc = _make_driver({"hA": 1}, 1, 2)
    workers = RecordingWorkers()
    driver.start(workers.fn)
    try:
        time.sleep(0.1)
        v1 = driver.world_version
        disc._hosts["hB"] = 1  # new host appears
        deadline = time.time() + 5
        while driver.world_version == v1 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.world_version > v1
        assert len(driver.current_assignments()) == 2
        upd = json.loads(rdv.get("discovery", "update"))
        assert upd["version"] >= v1
    finally:
        driver.stop()
        rdv.stop()


@pytest.mark.integration
def test_elastic_cli_end_to_end(tmp_path):
    """horovodrun --host-discovery-script with real worker processes
    (elastic_common.py analog, happy path)."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho localhost:2\n")
    script.chmod(0o755)
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os\n"
        "assert os.environ['HOROVOD_ELASTIC'] == '1'\n"
        "assert 'HOROVOD_RANK' in os.environ\n"
        "print('ELASTIC_WORKER_OK', os.environ['HOROVOD_RANK'])\n")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "--min-np", "2", "--max-np", "2",
         "--host-discovery-script", str(script),
         sys.executable, str(worker)],
        cwd=repo, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ELASTIC_WORKER_OK 0" in proc.stdout
    assert "ELASTIC_WORKER_OK 1" in proc.stdout


def test_concurrent_failures_coalesce_to_one_reset():
    """All slots of a dead host failing at once = ONE reshape (review
    finding: reset limit counts world reconfigurations)."""
    driver, rdv, disc = _make_driver({"hA": 2, "hB": 4}, 2, 6,
                                     reset_limit=1, cooldown_range=None)
    workers = RecordingWorkers()
    driver.start(workers.fn)
    try:
        time.sleep(0.1)
        # all 4 of hB's workers fail "simultaneously"
        for s in range(4):
            workers.finish("hB", s, 1)
        deadline = time.time() + 5
        while time.time() < deadline and \
                driver.registry.reset_count == 0:
            time.sleep(0.05)
        time.sleep(0.5)  # let any (wrong) extra resumes land
        assert driver.registry.reset_count <= 2  # not 4
        assert driver.error_message is None or \
            "Reset limit" not in (driver.error_message or "")
    finally:
        driver.stop()
        rdv.stop()


def test_host_removal_triggers_reactivation():
    """Discovery dropping a host must reshape the world and terminate its
    workers (review finding)."""
    driver, rdv, disc = _make_driver({"hA": 1, "hB": 1}, 1, 2,
                                     cooldown_range=None)
    workers = RecordingWorkers()
    driver.start(workers.fn)
    try:
        time.sleep(0.1)
        v1 = driver.world_version
        del disc._hosts["hB"]
        deadline = time.time() + 6
        while driver.world_version == v1 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.world_version > v1
        assignments = driver.current_assignments()
        assert all(s.hostname == "hA" for s in assignments)
    finally:
        driver.stop()
        rdv.stop()


def test_notification_seq_monotonic():
    driver, rdv, disc = _make_driver({"hA": 1}, 1, 1)
    workers = RecordingWorkers()
    driver.start(workers.fn)
    try:
        driver._notify_workers_host_changes(1)
        v1 = json.loads(rdv.get("discovery", "update"))["version"]
        driver._notify_workers_host_changes(1)
        v2 = json.loads(rdv.get("discovery", "update"))["version"]
        assert v2 > v1  # consecutive updates never share a version
    finally:
        driver.stop()
        rdv.stop()


ELASTIC_SCALEUP_WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys, os; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
# TpuState: carries a LIVE jax array through the backend reset (it must be
# re-materialized from the host commit on the new backend).
state = hvd.elastic.TpuState(params={{"w": jnp.full((2,), 3.0)}},
                             batch=0, sizes=[])

@hvd.elastic.run
def train(state):
    while state.batch < 15:
        out = hvd.allreduce(jnp.ones((2,)), op=hvd.Sum, name="g")
        state.sizes.append(int(float(out[0])))
        state.params = {{"w": state.params["w"] + 1.0}}
        state.batch += 1
        state.commit()
        if state.batch == 2 and hvd.rank() == 0:
            open({stepfile!r}, "w").close()  # signal: size-1 steps ran
        import time; time.sleep(0.8)
    return state.sizes

sizes = train(state)
w = float(state.params["w"][0])
print(f"WORKER done rank={{hvd.rank()}} final_size={{hvd.size()}} "
      f"w={{w}} sizes={{sizes}}", flush=True)
"""


@pytest.mark.integration
def test_elastic_scale_up_end_to_end(tmp_path):
    """A REAL scale-up: training starts at world size 1, discovery adds a
    host mid-run, the survivor re-rendezvouses, the new worker receives
    synced state, and both finish at size 2 (the full
    HostsUpdatedInterrupt → reset → jax.distributed re-init cycle)."""
    import subprocess
    import sys
    hosts_file = tmp_path / "hosts_now.txt"
    hosts_file.write_text("localhost:1\n")
    disc = tmp_path / "disc.sh"
    disc.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    disc.chmod(0o755)
    worker = tmp_path / "worker.py"
    stepfile = str(tmp_path / "first_steps_done")
    worker.write_text(ELASTIC_SCALEUP_WORKER.format(repo=REPO,
                                                    stepfile=stepfile))

    def scale_up():
        # Grow the world only after the size-1 world demonstrably trained
        # (marker after 2 committed steps): a fixed sleep raced the
        # worker's startup under full-suite load and the test then never
        # observed a size-1 allreduce.
        deadline = time.time() + 120
        while not os.path.exists(stepfile) and time.time() < deadline:
            time.sleep(0.25)
        hosts_file.write_text("localhost:2\n")

    t = threading.Thread(target=scale_up, daemon=True)
    t.start()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "--min-np", "1", "--max-np", "2",
         "--host-discovery-script", str(disc),
         sys.executable, str(worker)],
        cwd=REPO, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "WORKER done rank=0 final_size=2" in proc.stdout
    assert "WORKER done rank=1 final_size=2" in proc.stdout
    # The allreduce sums must show the world growing: some 1s then 2s.
    import re as _re
    m = _re.search(r"rank=0 final_size=2 w=18.0 sizes=\[([0-9, ]+)\]",
                   proc.stdout)
    assert m, proc.stdout[-2000:]
    sizes = [int(x) for x in m.group(1).split(",")]
    assert 1 in sizes and 2 in sizes and sizes == sorted(sizes)


FAILURE_RECOVERY_WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys, os; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
state = hvd.elastic.TpuState(params={{"w": jnp.zeros((2,))}}, batch=0)
crashed = {{"done": False}}

@hvd.elastic.run
def train(state):
    while state.batch < 10:
        out = hvd.allreduce(jnp.ones((2,)), op=hvd.Sum, name="g")
        assert abs(float(out[0]) - hvd.size()) < 1e-6
        state.params = {{"w": state.params["w"] + 1.0}}
        state.batch += 1
        if state.batch % 2 == 0:
            state.commit()
        if state.batch == 5 and not crashed["done"]:
            crashed["done"] = True
            raise hvd.HorovodInternalError("simulated ICI fault")
    return float(state.params["w"][0])

w = train(state)
print(f"rank{{hvd.rank()}} RECOVERED size={{hvd.size()}} "
      f"batches={{state.batch}} w={{w}}", flush=True)
assert state.batch == 10 and w == 10.0
"""


@pytest.mark.integration
def test_failure_recovery_same_world(tmp_path):
    """HorovodInternalError with UNCHANGED membership: every rank restores
    the last commit, re-initializes the runtime at the same world size
    (fresh negotiation generation — stale KV records must not be consumed),
    and completes with exact state."""
    import subprocess
    import sys
    disc = tmp_path / "disc.sh"
    disc.write_text("#!/bin/sh\necho localhost:2\n")
    disc.chmod(0o755)
    worker = tmp_path / "worker.py"
    worker.write_text(FAILURE_RECOVERY_WORKER.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "--min-np", "2", "--max-np", "2",
         "--host-discovery-script", str(disc),
         sys.executable, str(worker)],
        cwd=REPO, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "rank0 RECOVERED size=2 batches=10 w=10.0" in proc.stdout
    assert "rank1 RECOVERED size=2 batches=10 w=10.0" in proc.stdout


# ---------------------------------------------------------------------------
# ElasticSampler (torch/elastic/sampler.py:24 analog; unit tests follow the
# test_torch_elastic.py pattern — no cluster needed)
# ---------------------------------------------------------------------------

def test_elastic_sampler_full_epoch_coverage():
    from horovod_tpu.elastic import ElasticSampler
    samplers = []
    for r in range(2):
        s = ElasticSampler(20, shuffle=True, seed=3)
        s.reset(rank=r, size=2)
        samplers.append(s)
    union = set(samplers[0].indices) | set(samplers[1].indices)
    assert union == set(range(20))
    assert len(samplers[0]) == len(samplers[1]) == 10


def test_elastic_sampler_mid_epoch_reshape():
    """Shrink 3 -> 2 mid-epoch: processed prefix never reappears, the
    remaining permutation is fully covered by the new shards."""
    import random as _random
    from horovod_tpu.elastic import ElasticSampler
    N, B = 30, 2
    s0 = ElasticSampler(N, shuffle=True, seed=7)
    s0.reset(rank=0, size=3)
    # world of 3 processes 3 batches of B per rank
    for b in range(3):
        s0.record_batch(b, B)
    assert s0.processed_num == 3 * B * 3
    st = s0.state_dict()

    perm = list(range(N))
    _random.Random(7 + 0).shuffle(perm)
    processed_prefix = set(perm[:s0.processed_num])

    new_shards = []
    for r in range(2):
        s = ElasticSampler(N, shuffle=True, seed=7)
        s.load_state_dict(st)
        s.reset(rank=r, size=2)
        new_shards.append(set(s.indices))
    covered = new_shards[0] | new_shards[1]
    assert covered == set(perm[s0.processed_num:])
    assert not (covered & processed_prefix)


def test_elastic_sampler_set_epoch_clears_progress():
    from horovod_tpu.elastic import ElasticSampler
    s = ElasticSampler(12, shuffle=True, seed=1)
    s.reset(rank=0, size=2)
    s.record_batch(0, 3)
    assert s.processed_num == 6
    order_e0 = list(s.indices)
    s.set_epoch(1)
    s.reset(rank=0, size=2)
    assert s.processed_num == 0 and len(s) == 6
    assert list(s.indices) != order_e0  # reshuffled


SCALE_DOWN_UP_WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys, os; sys.path.insert(0, {repo!r})
import time
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
N, B = 240, 2
sampler = hvd.elastic.ElasticSampler(N, shuffle=True, seed=5)
state = hvd.elastic.TpuState(params={{"w": jnp.zeros((2,))}},
                             sampler=sampler.state_dict(),
                             sizes=[], total=0.0)

@hvd.elastic.run
def train(state):
    sampler.load_state_dict(state.sampler)
    bidx = 0
    while True:
        idxs = sampler.get_indices(bidx, B)
        if not idxs:
            break
        out = hvd.allreduce(jnp.ones((2,)), op=hvd.Sum, name="g")
        state.sizes = state.sizes + [int(float(out[0]))]
        state.total = state.total + float(out[0])
        state.params = {{"w": state.params["w"] + 1.0}}
        sampler.record_batch(bidx, B)
        bidx += 1
        state.sampler = sampler.state_dict()
        state.commit()
        # Progress markers gate the test's reshape thread (a fixed sleep
        # raced worker startup under load and the shrink went unobserved).
        if hvd.rank() == 0 and state.sizes.count(3) >= 2:
            open({m3!r}, "w").close()
        if hvd.rank() == 0 and state.sizes.count(2) >= 2:
            open({m2!r}, "w").close()
        time.sleep(0.45)
    return state.sizes

sizes = train(state)
ok_total = abs(state.total - sum(sizes)) < 1e-6
print(f"SDWORKER done rank={{hvd.rank()}} size={{hvd.size()}} "
      f"processed={{sampler.processed_num}} total_ok={{ok_total}} "
      f"sizes={{sizes}}", flush=True)
"""


@pytest.mark.integration
@pytest.mark.slow  # ~35s e2e; also the contention-flaky one (TODO.md) — keep out of the gating tier
def test_elastic_scale_down_then_up_end_to_end(tmp_path):
    """VERDICT r1 item 4: slot-granular scale-DOWN on a single host
    (localhost:3 -> localhost:2) without killing the job, then growth back
    to 3.  The decommissioned worker must not be recorded as a failure
    (which would blacklist localhost and abort); survivors re-rendezvous
    with state and mid-epoch sampler progress intact."""
    import subprocess
    import sys
    hosts_file = tmp_path / "hosts_now.txt"
    hosts_file.write_text("localhost:3\n")
    disc = tmp_path / "disc.sh"
    disc.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    disc.chmod(0o755)
    worker = tmp_path / "worker.py"
    m3 = str(tmp_path / "trained_at_3")
    m2 = str(tmp_path / "trained_at_2")
    worker.write_text(SCALE_DOWN_UP_WORKER.format(repo=REPO, m3=m3, m2=m2))

    reshape_log: list = []

    def reshape():
        # Shrink only after the 3-world demonstrably trained, grow back
        # only after the 2-world did (markers written by rank 0).  Every
        # wait is bounded AND diagnosed: a missed marker records which
        # phase never arrived and leaves the world alone, instead of the
        # old silent fallthrough that reshaped anyway and made a slow
        # 3-world read as a mid-shrink wedge (TODO.md contention flake).
        deadline = time.time() + 180
        while not os.path.exists(m3):
            if time.time() >= deadline:
                reshape_log.append(
                    "TIMEOUT waiting for the 3-world progress marker "
                    "(rank 0 never logged two size-3 steps in 180 s); "
                    "world left at localhost:3, no shrink attempted")
                return
            time.sleep(0.25)
        reshape_log.append("3-world trained; shrinking to localhost:2")
        hosts_file.write_text("localhost:2\n")
        while not os.path.exists(m2):
            if time.time() >= deadline:
                reshape_log.append(
                    "TIMEOUT waiting for the 2-world progress marker "
                    "after the shrink (rank 0 never logged two size-2 "
                    "steps); world left at localhost:2, no regrow")
                return
            time.sleep(0.25)
        reshape_log.append("2-world trained; growing back to localhost:3")
        hosts_file.write_text("localhost:3\n")

    t = threading.Thread(target=reshape, daemon=True)
    t.start()
    env = dict(os.environ)
    env["HOROVOD_GLOO_TIMEOUT_SECONDS"] = "45"  # stall recovery with
    # headroom against spurious full-suite-load stalls (see crash test).
    # TODO.md contention-class flake: at 30 s a slow-but-alive gloo
    # re-init under full-suite load looked stalled once, cascading a
    # spurious reset that outlived the old 480 s budget.
    # Worker-side deadlines must sit WELL inside the subprocess budget:
    # under full-suite CPU load, gloo re-inits and negotiation rounds run
    # several times slower than in isolation (this test: 53 s alone).
    env["HOROVOD_ELASTIC_TIMEOUT"] = "240"
    # A worker wedged in a dead world's shutdown barrier otherwise rides
    # out the 60 s default on every reshape (crash-test rationale).
    env["HVD_TPU_DIST_SHUTDOWN_TIMEOUT_S"] = "10"
    proc = run_world(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "--min-np", "2", "--max-np", "3",
         "--host-discovery-script", str(disc),
         sys.executable, str(worker)],
        # 900 s: the crash test's budget reasoning — healthy runs finish
        # in ~60 s, the headroom only pays off under pathological load.
        timeout=900, env=env, tag="scale_down")
    # The worker has exited, so the reshape thread is either done or
    # stuck in a wait it will diagnose; give it a beat and surface its
    # phase log with every failure (which marker was reached tells a
    # wedged shrink apart from a world that never trained).
    t.join(timeout=10)
    reshape_note = "; ".join(reshape_log) or \
        "reshape thread recorded no phase (never observed the 3-world " \
        "marker and still inside its bounded wait)"
    assert proc.returncode == 0, (
        f"[reshape phases: {reshape_note}]\n"
        + proc.stdout[-4000:] + proc.stderr[-2000:])
    import re as _re
    done = _re.findall(r"SDWORKER done rank=(\d) size=(\d) "
                       r"processed=(\d+) total_ok=(\w+) sizes=\[([0-9, ]*)\]",
                       proc.stdout)
    assert done, f"[reshape phases: {reshape_note}]\n" + proc.stdout[-4000:]
    # Every finishing rank saw the same world trajectory with a shrink.
    for rank_, size_, processed, total_ok, sizes_s in done:
        sizes = [int(x) for x in sizes_s.split(",")]
        assert total_ok == "True"
        assert 3 in sizes and 2 in sizes, sizes
        # shrink happened after growth start: pattern 3... 2... (maybe 3...)
        first2 = sizes.index(2)
        assert all(s == 3 for s in sizes[:first2]), sizes
        assert int(processed) >= 240  # full epoch completed (with padding)


# ---------------------------------------------------------------------------
# Disk spill: elastic state surviving ABRUPT peer death (TODO.md parity gap —
# a crashed peer FATALs survivors' jax.distributed clients, so the in-memory
# commit dies with the process; the spill file is the copy that survives)
# ---------------------------------------------------------------------------

def test_state_spill_roundtrip(tmp_path, hvd8):
    spill = str(tmp_path / "spill")
    state = E.TpuState(spill_dir=spill,
                       params={"w": jnp.ones((3,), jnp.float32)}, epoch=0)
    state.params = {"w": state.params["w"] * 4}
    state.epoch = 7
    state.commit()
    # A FRESH incarnation (same worker identity, new process) adopts the
    # on-disk commit because it is ahead of its own seq 0.
    fresh = E.TpuState(spill_dir=spill,
                       params={"w": jnp.zeros((3,), jnp.float32)}, epoch=0)
    assert fresh.load_spill() is True
    assert fresh._commit_seq == 1
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), 4 * np.ones(3))
    assert fresh.epoch == 7
    # The committing state itself must NOT re-adopt its own spill (not ahead).
    assert state.load_spill() is False
    # clear_spill removes the file; a later fresh state finds nothing.
    state.clear_spill()
    later = E.TpuState(spill_dir=spill,
                       params={"w": jnp.zeros((3,), jnp.float32)}, epoch=0)
    assert later.load_spill() is False


def test_state_spill_torn_write_ignored(tmp_path, hvd8):
    spill = str(tmp_path / "spill")
    state = E.ObjectState(spill_dir=spill, step=3)
    state.commit()
    path = state._spill_path()
    # Corrupt the published file: load must fall back to in-memory state
    # (a torn write can only ever affect the .tmp, but guard the reader too).
    with open(path, "wb") as f:
        f.write(b"\x80garbage")
    fresh = E.ObjectState(spill_dir=spill, step=0)
    assert fresh.load_spill() is False
    assert fresh.step == 0


def test_state_spill_disabled_without_dir(hvd8):
    state = E.ObjectState(step=1)
    state.commit()  # no spill dir: must be a no-op, not an error
    assert state._spill_path() is None
    assert state.load_spill() is False


CRASH_WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys, os; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
state = hvd.elastic.TpuState(params={{"w": jnp.zeros((2,))}}, batch=0)
seen = {{}}

@hvd.elastic.run
def train(state):
    if "first_batch" not in seen:
        seen["first_batch"] = state.batch
    while state.batch < 10:
        out = hvd.allreduce(jnp.ones((2,)), op=hvd.Sum, name="g")
        state.params = {{"w": state.params["w"] + 1.0}}
        state.batch += 1
        if state.batch % 2 == 0:
            state.commit()
        if state.batch == 5 and hvd.rank() == 1 \\
                and not os.path.exists({marker!r}):
            open({marker!r}, "w").close()
            os._exit(1)   # ABRUPT death: no exception, no graceful exit
    return float(state.params["w"][0])

w = train(state)
print(f"rank{{hvd.rank()}} CRASHSURVIVED size={{hvd.size()}} "
      f"batches={{state.batch}} w={{w}} first_batch={{seen['first_batch']}}",
      flush=True)
"""


@pytest.mark.slow  # ~107s: full multi-process crash/respawn cycle
@pytest.mark.integration
def test_abrupt_crash_resumes_from_spill(tmp_path):
    """TODO.md parity gap closed: rank 1 dies with os._exit (no graceful
    path), survivors either recover in place or are FATALed by the
    coordination service and respawned by the driver — in every outcome the
    job completes with state continuity because commits were spilled to
    disk.  The respawned incarnation must resume from the last commit
    (batch 4), not from scratch."""
    import subprocess
    import sys
    disc = tmp_path / "disc.sh"
    disc.write_text("#!/bin/sh\necho localhost:2\n")
    disc.chmod(0o755)
    marker = str(tmp_path / "crashed.marker")
    worker = tmp_path / "worker.py"
    worker.write_text(CRASH_WORKER.format(repo=REPO, marker=marker))
    env = dict(os.environ)
    env["HVD_TPU_ELASTIC_SPILL_DIR"] = str(tmp_path / "spill")
    # 30 s: fast-but-not-hair-trigger stall recovery.  At 20 s, full-suite
    # load made slow-but-alive negotiations look stalled, cascading
    # spurious resets that could outlast even the 900 s budget.
    env["HOROVOD_GLOO_TIMEOUT_SECONDS"] = "30"
    # A doomed survivor dies in the failed shutdown barrier; bound it so
    # the respawn cycle converges inside the test budget.
    env["HVD_TPU_DIST_SHUTDOWN_TIMEOUT_S"] = "10"
    proc = run_world(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "--min-np", "2", "--max-np", "2",
         "--host-discovery-script", str(disc),
         "--blacklist-cooldown-range", "1", "3",
         sys.executable, str(worker)],
        # 900 s: alone this finishes in ~35 s, but the full-suite runs
        # share one host core with concurrently-running test files; the
        # round-3 suite saw the old 420 s budget exceeded purely from
        # load (the test then passed in isolation).  The generous budget
        # costs nothing when healthy — the run exits as soon as it
        # converges.
        timeout=900, env=env, tag="abrupt_crash")
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-3000:]
    import re as _re
    done = _re.findall(
        r"rank(\d) CRASHSURVIVED size=(\d) batches=(\d+) w=([0-9.]+) "
        r"first_batch=(\d+)", proc.stdout)
    assert len(done) == 2, proc.stdout[-4000:]
    for rank_, size_, batches, w, first_batch in done:
        assert int(size_) == 2 and int(batches) == 10 and float(w) == 10.0
    # At least the crashed worker's replacement resumed from the on-disk
    # commit (batch 4), proving the spill — not a from-scratch restart.
    assert any(int(fb) == 4 for *_, fb in done), done
