"""faultline unit suite (ISSUE 6): seeded plans, every injection point,
and the self-healing paths they exercise.

The chaos soak (tests/test_faultline_soak.py, ``slow``) proves the
multi-fault convergence story end to end; this file pins each piece in
isolation and fast enough for tier-1:

* plan determinism — identical seed → identical schedule → identical
  firing sequence (the acceptance artifact);
* engine injections (poison-step / slow-decode / pool-corrupt-block) and
  the recovery each must trigger;
* KV client retry/backoff — transient transport faults retried with the
  ``HVD_KV_RETRY_*`` budget, 4xx answered without a retry;
* deadline propagation — a doomed request is never prefilled, an
  in-flight request dies at its deadline and frees its slot + blocks;
* scale-up — ``mark_alive`` / ``report_rank_recovered`` /
  ``add_replica`` and the hardened ``watch_preemption`` loop that feeds
  them.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import faultline as fl
from horovod_tpu.faultline.plan import FaultInjected
from horovod_tpu.models import create_mlp
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.serve import (DeadlineExceededError, DynamicBatcher,
                               InferenceEngine, MLPAdapter, Replica,
                               ReplicaScheduler, Request, ServeMetrics,
                               ServeServer, TransformerAdapter)

VOCAB = 31


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    fl.uninstall()
    yield
    fl.uninstall()


def _mlp_adapter(seed=3, vocab=VOCAB, max_len=128):
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return MLPAdapter(mlp, params, vocab_size=vocab, max_len=max_len)


class _SlowMLP(MLPAdapter):
    """MLP adapter with a visible per-decode-step cost, so a request can
    be held in flight long enough to fault deterministically."""

    delay_s = 0.02

    def decode(self, cache, tokens, positions):
        time.sleep(self.delay_s)
        return MLPAdapter.decode(self, cache, tokens, positions)

    def decode_paged(self, cache, tokens, positions, tables):
        time.sleep(self.delay_s)
        return MLPAdapter.decode(self, cache, tokens, positions)


def _slow_adapter(seed=3, vocab=VOCAB):
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return _SlowMLP(mlp, params, vocab_size=vocab, max_len=256)


def _engine(adapter=None, replica_id="replica-f", **kw):
    kw.setdefault("max_batch", 4)
    return InferenceEngine(adapter or _mlp_adapter(),
                           metrics=ServeMetrics(),
                           replica_id=replica_id, **kw)


# -- plan: schedule, determinism, grammar, env -------------------------------

def _three_specs():
    return [fl.FaultSpec("poison-step", target="replica-0"),
            fl.FaultSpec("drop-kv-response", repeat=2),
            fl.FaultSpec("kill-rank", target="h0", repeat=3)]


def test_plan_same_seed_same_schedule_and_firing_sequence():
    p1 = fl.FaultPlan(_three_specs(), seed=7)
    p2 = fl.FaultPlan(_three_specs(), seed=7)
    assert p1.schedule() == p2.schedule()
    for p in (p1, p2):
        for _ in range(fl.HORIZON + 8):
            p.fire("engine.step", "replica-0")
            p.fire("kv.request", "a:1")
            p.fire("preempt.poll", "h0")
    assert p1.firing_sequence() == p2.firing_sequence()
    assert len(p1.firing_sequence()) == 1 + 2 + 3  # every window fired
    assert p1.exhausted() and p2.exhausted()


def test_plan_different_seed_different_schedule():
    # 3 specs over a 16-step horizon: seeds 0..9 all landing on seed 7's
    # exact schedule is ~(1/16)^3 per seed — astronomically unlikely.
    base = fl.FaultPlan(_three_specs(), seed=7).schedule()
    assert any(fl.FaultPlan(_three_specs(), seed=s).schedule() != base
               for s in range(10))


def test_plan_explicit_step_does_not_reshuffle_others():
    """The rng draw happens for every spec, so pinning one spec's step
    leaves the seeded steps of the rest untouched."""
    loose = fl.FaultPlan(_three_specs(), seed=3).schedule()
    specs = _three_specs()
    specs[0].step = 2
    pinned = fl.FaultPlan(specs, seed=3).schedule()
    assert pinned[0]["step"] == 2
    assert [s["step"] for s in pinned[1:]] == \
        [s["step"] for s in loose[1:]]


def test_plan_copies_specs_so_reuse_is_pure():
    """FaultPlan must not mutate the caller's FaultSpec objects: a spec
    list reused across plans (a repeat-soak harness) gets a fresh step
    assignment and fresh firing state each time."""
    specs = [fl.FaultSpec("poison-step", target="r0")]
    p1 = fl.FaultPlan(specs, seed=1)
    for _ in range(fl.HORIZON + 2):
        p1.fire("engine.step", "r0")
    assert p1.exhausted()
    assert specs[0].step is None and specs[0].fired == 0  # untouched
    p2 = fl.FaultPlan(specs, seed=1)
    assert not p2.exhausted()
    for _ in range(fl.HORIZON + 2):
        p2.fire("engine.step", "r0")
    assert p2.firing_sequence() == p1.firing_sequence()  # and re-fires


def test_plan_target_and_instance_filtering():
    plan = fl.FaultPlan([fl.FaultSpec("poison-step", step=1,
                                      target="replica-1")], seed=0)
    # replica-0's counter crossing index 1 must NOT fire replica-1's
    # fault (and must not consume it either).
    for _ in range(4):
        assert plan.fire("engine.step", "replica-0") == []
    assert plan.fire("engine.step", "replica-1") == []       # index 0
    assert [f.kind for f in plan.fire("engine.step", "replica-1")] == \
        ["poison-step"]                                      # index 1
    assert plan.fire("engine.step", "replica-1") == []       # exhausted


def test_parse_plan_grammar():
    plan = fl.parse_plan(
        "kill-rank:h3@4*3, drop-kv-response@1*2, slow-decode~0.05,"
        "poison-step:replica-1/replica.route", seed=1)
    d = plan.schedule()
    assert d[0] == {"kind": "kill-rank", "point": "preempt.poll",
                    "step": 4, "target": "h3", "repeat": 3, "param": 0.0,
                    "fired": 0}
    assert (d[1]["step"], d[1]["repeat"]) == (1, 2)
    assert d[2]["param"] == 0.05
    assert d[3]["point"] == "replica.route"
    # Suffix markers are order-insensitive (each at most once).
    flipped = fl.parse_spec("slow-decode~0.08@2").to_dict()
    assert (flipped["step"], flipped["param"]) == (2, 0.08)
    with pytest.raises(ValueError):
        fl.parse_spec("no-such-fault")
    with pytest.raises(ValueError):
        fl.parse_spec("poison-step/nowhere")
    with pytest.raises(ValueError):
        fl.parse_spec("slow-decode@1@2")


def test_env_bootstrap_installs_once(monkeypatch):
    import horovod_tpu.faultline.runtime as rt
    monkeypatch.setenv("HVD_FAULTLINE_PLAN", "poison-step:replica-9@2")
    monkeypatch.setenv("HVD_FAULTLINE_SEED", "5")
    monkeypatch.setattr(rt, "_env_checked", False)
    plan = fl.maybe_install_from_env()
    assert plan is not None and fl.active_plan() is plan
    assert fl.fire("engine.step", "replica-9") == []  # step 0
    assert fl.fire("engine.step", "replica-9") == []  # step 1
    assert [f.kind for f in fl.fire("engine.step", "replica-9")] == \
        ["poison-step"]
    # A second bootstrap never replaces the active plan.
    assert fl.maybe_install_from_env() is plan


def test_fault_firings_land_in_the_timeline(tmp_path):
    from horovod_tpu.timeline import Timeline
    path = str(tmp_path / "fault_trace.json")
    tl = Timeline(path)
    plan = fl.FaultPlan([fl.FaultSpec("slow-decode", step=0)], seed=0)
    plan.set_timeline(tl)
    plan.fire("engine.step", "replica-0")
    tl.close()
    events = json.load(open(path))
    (ev,) = [e for e in events
             if e.get("name", "").startswith("FAULTLINE/")]
    assert ev["name"] == "FAULTLINE/slow-decode"
    assert ev["args"] == {"point": "engine.step",
                          "instance": "replica-0", "step": 0}


# -- engine injection point --------------------------------------------------

def test_poison_step_fails_inflight_and_engine_recovers():
    eng = _engine(_slow_adapter()).start()
    try:
        victim = Request([3], max_new_tokens=200)
        eng.batcher.submit(victim)
        deadline = time.monotonic() + 30
        while eng.active_count == 0 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert eng.active_count == 1
        # Installed mid-flight: the fault fires on the NEXT iteration, so
        # the victim is deterministically in the poisoned batch.
        fl.install(fl.FaultPlan(
            [fl.FaultSpec("poison-step", step=0, target="replica-f")]))
        with pytest.raises(FaultInjected):
            victim.result(timeout=30)
        # One poisoned batch must not take the replica down.
        after = eng.generate([5], max_new_tokens=4, timeout_s=30)
        assert len(after) == 4
        snap = eng.metrics.snapshot()
        assert snap["requests"]["error"] == 1
        assert snap["requests"]["ok"] == 1
        assert fl.active_plan().firing_sequence() == \
            [("engine.step", 0, "poison-step")]
    finally:
        eng.stop()


def test_slow_decode_stalls_but_serves_correctly():
    eng = _engine().start()
    try:
        baseline = eng.generate([7], max_new_tokens=4, timeout_s=30)
        fl.install(fl.FaultPlan(
            [fl.FaultSpec("slow-decode", step=0, target="replica-f",
                          param=0.15)]))
        t0 = time.monotonic()
        out = eng.generate([7], max_new_tokens=4, timeout_s=30)
        assert out == baseline           # a stall never changes tokens
        assert time.monotonic() - t0 >= 0.14  # the injected stall landed
        assert fl.active_plan().exhausted()
    finally:
        eng.stop()


_TINY = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_len=64, causal=True,
                          dtype=jnp.float32, scan_layers=False)


def test_pool_corrupt_block_scrubs_prefix_cache_and_stays_exact():
    model = Transformer(_TINY)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ad = TransformerAdapter(_TINY, params, block_tokens=8)
    eng = _engine(ad, kv_mode="paged", prefill_chunk=16).start()
    try:
        prompt = list(range(1, 25))  # 3 full blocks of 8
        first = eng.generate(prompt, max_new_tokens=4, timeout_s=60)
        assert eng.kv_stats()["retained"] > 0  # prompt blocks cached
        fl.install(fl.FaultPlan(
            [fl.FaultSpec("pool-corrupt-block", step=0,
                          target="replica-f", param=99)]))
        deadline = time.monotonic() + 30
        # Poll the OUTCOME (registry scrubbed), not just exhausted():
        # fire() marks the spec fired before the engine's handler runs
        # the scrub, so exhausted-then-check races the handler.
        while eng.kv_stats()["retained"] > 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert fl.active_plan().exhausted()
        assert eng.kv_stats()["retained"] == 0  # registry scrubbed
        # The same prompt re-prefills from scratch and matches exactly —
        # a corrupted block is DROPPED, never served stale.
        assert eng.generate(prompt, max_new_tokens=4,
                            timeout_s=60) == first
    finally:
        eng.stop()


def test_block_manager_invalidate_retained_skips_referenced_blocks():
    from horovod_tpu.serve import BlockManager, chain_hashes
    bm = BlockManager(8, 4, prefix_cache=True)
    held = bm.allocate(2)
    hashes = chain_hashes(list(range(8)), 4)
    bm.register(hashes[0], held[0])
    bm.register(hashes[1], held[1])
    bm.free(held[0])                  # retained (refcount 0, registered)
    assert bm.stats()["retained"] == 1
    assert bm.invalidate_retained(5) == 1   # only the retained one
    assert bm.stats()["retained"] == 0
    assert bm.refcount(held[1]) == 1        # live block untouched
    assert bm.lookup_prefix(list(range(8)),
                            hashes=hashes)[0] != [held[0]]


# -- deadline propagation ----------------------------------------------------

def test_doomed_request_is_never_prefilled():
    eng = _engine()
    doomed = Request([4], max_new_tokens=8, timeout_s=0.05)
    eng.batcher.submit(doomed)
    time.sleep(0.1)                # budget dies while queued
    eng.start()
    try:
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=10)
        snap = eng.metrics.snapshot()
        assert snap["prefills"] == 0          # never prefilled
        assert snap["requests"]["expired"] == 1
    finally:
        eng.stop()


def test_inflight_deadline_expires_and_frees_the_slot():
    eng = _engine(_slow_adapter()).start()
    try:
        r = Request([3], max_new_tokens=200, timeout_s=0.3)
        eng.batcher.submit(r)
        with pytest.raises(DeadlineExceededError) as ei:
            r.result(timeout=30)
        assert "mid-flight" in str(ei.value)
        assert 0 < len(r.generated) < 200    # really died mid-decode
        deadline = time.monotonic() + 10
        while eng.active_count and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.active_count == 0         # slot freed immediately
        assert eng.metrics.snapshot()["requests"]["expired"] == 1
        # The engine keeps serving within-budget requests.
        assert len(eng.generate([5], max_new_tokens=3,
                                timeout_s=30)) == 3
    finally:
        eng.stop()


def test_request_rejects_non_positive_timeout():
    with pytest.raises(ValueError):
        Request([1], timeout_s=0)
    with pytest.raises(ValueError):
        Request([1], timeout_s=-3)
    assert Request([1], timeout_s=5).remaining() <= 5.0
    assert Request([1]).remaining() is None


# -- HTTP deadline surface ---------------------------------------------------

def _post(port, payload, headers=()):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST",
        headers=dict({"Content-Type": "application/json"}, **dict(headers)))
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def _two_replica_server(adapter_fn=_mlp_adapter):
    replicas = [Replica(f"replica-{i}", None,
                        _engine(adapter_fn(), replica_id=f"replica-{i}"))
                for i in range(2)]
    metrics = replicas[0].engine.metrics
    sched = ReplicaScheduler(replicas, metrics=metrics)
    server = ServeServer(sched, request_timeout_s=60)
    port = server.start(port=0, host="127.0.0.1")
    return server, sched, port


def test_http_non_positive_timeout_is_400_not_a_parked_handler():
    server, _, port = _two_replica_server()
    try:
        for bad in (0, -1, "0"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(port, {"tokens": [1, 2], "timeout_s": bad})
            assert ei.value.code == 400, bad
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"tokens": [1, 2]},
                  headers=[("X-Request-Timeout-S", "-2")])
        assert ei.value.code == 400
    finally:
        server.stop()


def test_http_header_timeout_propagates_and_504_carries_budget():
    server, _, port = _two_replica_server(_slow_adapter)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"tokens": [1, 2], "max_new_tokens": 200},
                  headers=[("X-Request-Timeout-S", "0.3")])
        assert ei.value.code == 504
        # The header reached Request.deadline (the engine killed it, not
        # the server-side 60 s cap) and the shed reports the spent budget.
        assert ei.value.headers["X-Deadline-Remaining-S"] == "0.000"
        assert ei.value.headers["Retry-After"] == "0"
    finally:
        server.stop()


def test_http_503_carries_remaining_budget_header():
    server, sched, port = _two_replica_server()
    try:
        sched.mark_dead("replica-0")
        sched.mark_dead("replica-1")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"tokens": [1], "timeout_s": 30})
        assert ei.value.code == 503
        # Retry-After stays the MINIMUM-wait availability hint (capped
        # by the budget — advertising the full budget there would make
        # a compliant client sleep it away); the exact budget rides the
        # X- header.
        assert ei.value.headers["Retry-After"] == "1"
        assert 25 < float(ei.value.headers["X-Deadline-Remaining-S"]) <= 30
        # Legacy flat hint without a client deadline.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"tokens": [1]})
        assert ei.value.headers["Retry-After"] == "1"
        assert "X-Deadline-Remaining-S" not in ei.value.headers
    finally:
        server.stop()


# -- scale-up: mark_alive / add_replica / recovered ranks --------------------

def test_mark_alive_reopens_batcher_and_rejoins_routing():
    server, sched, port = _two_replica_server()
    try:
        sched.mark_dead("replica-0", reason="test kill")
        health = sched.healthz()
        assert health["status"] == "degraded"
        out_degraded = _post(port, {"tokens": [3, 4]})
        assert out_degraded["replica"] == "replica-1"

        sched.mark_alive("replica-0", reason="test recovery")
        assert sched.healthz()["status"] == "ok"
        snap = sched.metrics.snapshot()
        assert snap["replica_events"] == {"mark_dead": 1, "mark_alive": 1}
        # The revived batcher accepts and its engine answers: load
        # replica-1 so least-loaded routing picks the empty revival.
        r1 = sched.replicas[1]
        blocker = Request([2] * 3, max_new_tokens=120)
        r1.engine.batcher.submit(blocker)
        out = _post(port, {"tokens": [3, 4]})
        assert out["replica"] == "replica-0"
        assert out["tokens"] == out_degraded["tokens"]  # exactness holds
        blocker.result(timeout=30)
        # Idempotent on a healthy replica.
        sched.mark_alive("replica-0")
        assert sched.metrics.snapshot()["replica_events"]["mark_alive"] == 1
    finally:
        server.stop()


def test_add_replica_scales_the_fleet_up():
    server, sched, port = _two_replica_server()
    try:
        new = Replica("replica-2", None,
                      _engine(_mlp_adapter(), replica_id="replica-2"))
        sched.add_replica(new)
        health = sched.healthz()
        assert health["total"] == 3 and health["status"] == "ok"
        # The new engine was started (scheduler already running) and
        # serves through the normal routing path.
        for r in sched.replicas[:2]:
            r.engine.batcher.submit(Request([2] * 3, max_new_tokens=120))
        out = _post(port, {"tokens": [5]})
        assert out["replica"] == "replica-2"
        with pytest.raises(ValueError):
            sched.add_replica(Replica("replica-2", None, _engine()))
    finally:
        server.stop()


def test_report_rank_recovered_maps_rank_to_dead_replica():
    import types
    replicas = [Replica(f"replica-{i}",
                        types.SimpleNamespace(ranks=[2 * i, 2 * i + 1],
                                              size=lambda: 2),
                        _engine(replica_id=f"replica-{i}"))
                for i in range(2)]
    sched = ReplicaScheduler(replicas,
                             metrics=replicas[0].engine.metrics).start()
    try:
        assert sched.report_rank_lost(3) == "replica-1"
        assert sched.healthz()["status"] == "degraded"
        assert sched.report_rank_recovered(5) is None  # no such replica
        assert sched.report_rank_recovered(2) == "replica-1"
        assert sched.healthz()["status"] == "ok"
    finally:
        sched.stop()


# -- hardened preemption watcher ---------------------------------------------

class _ScriptedKV:
    """scan() plays a script: exceptions raise, dicts return; the last
    entry repeats forever."""

    def __init__(self, script):
        self.script = list(script)

    def scan(self, scope):
        item = self.script.pop(0) if len(self.script) > 1 \
            else self.script[0]
        if isinstance(item, Exception):
            raise item
        return item


def test_watcher_survives_kv_errors_counts_them_and_heals_the_fleet():
    import types
    replicas = [Replica(f"replica-{i}",
                        types.SimpleNamespace(ranks=[i], size=lambda: 1),
                        _engine(replica_id=f"replica-{i}"))
                for i in range(2)]
    sched = ReplicaScheduler(replicas,
                             metrics=replicas[0].engine.metrics).start()
    kv = _ScriptedKV([OSError("flake 1"), OSError("flake 2"),
                      {"h0": b"TERMINATE"}, {"h0": b"TERMINATE"}, {}])
    try:
        sched.watch_preemption(kv, {"h0": [0]}, poll_s=0.01)
        deadline = time.monotonic() + 30
        # Poll the monotonic transition counters to their final values —
        # not the transient "degraded" status (the scripted clearance
        # re-heals within ~2 polls, so a loaded box can miss the window)
        # and not state flags (mark_alive flips state before counting).
        want = {"mark_dead": 1, "mark_alive": 1}
        while sched.metrics.snapshot()["replica_events"] != want \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        while sched.healthz()["status"] != "ok" \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sched.healthz()["status"] == "ok"
        snap = sched.metrics.snapshot()
        assert snap["preempt_poll_errors"] == 2
        assert snap["replica_events"] == want
        metrics_text = sched.metrics.render()
        assert "hvd_serve_preempt_poll_errors_total 2" in metrics_text
        assert ('hvd_serve_replica_events_total{event="mark_alive"} 1'
                in metrics_text)
    finally:
        sched.stop()


# -- KV client retry/backoff -------------------------------------------------

@pytest.fixture()
def kv_world(monkeypatch):
    from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer
    monkeypatch.setenv("HVD_TPU_KV_SERVER", "python")
    monkeypatch.setenv("HVD_KV_RETRY_MAX", "3")
    monkeypatch.setenv("HVD_KV_RETRY_BASE_MS", "1")
    monkeypatch.setenv("HVD_KV_RETRY_CAP_MS", "5")
    server = KVStoreServer()
    port = server.start(0)
    client = KVStoreClient("127.0.0.1", port)
    yield server, client
    server.stop()


def test_kv_retry_survives_a_drop_train_within_budget(kv_world):
    _, client = kv_world
    assert client.retry_max == 3
    plan = fl.install(fl.FaultPlan(
        [fl.FaultSpec("drop-kv-response", step=1, repeat=2)]))
    client.put("s", "k", b"v")                 # attempt 0: clean
    assert client.get("s", "k") == b"v"        # attempts 1,2 dropped;
    assert plan.exhausted()                    # 3rd succeeds


def test_kv_retry_exhaustion_raises_the_transport_error(kv_world):
    _, client = kv_world
    fl.install(fl.FaultPlan(
        [fl.FaultSpec("drop-kv-response", step=0, repeat=3)]))
    with pytest.raises(ConnectionError):
        client.get("s", "nope")
    # The drop train consumed the whole retry budget: 3 attempts.
    assert fl.active_plan().count("kv.request", "127.0.0.1:"
                                  + str(client.port)) == 3
    # The next request reconnects and works (poisoned socket dropped).
    client.put("s", "k2", b"w")
    assert client.get("s", "k2") == b"w"


def test_kv_4xx_is_fatal_not_retried(kv_world):
    _, client = kv_world
    plan = fl.install(fl.FaultPlan([]))  # counters only
    status, _ = client._request("POST", "/scope", body=b"{not json")
    assert status == 400                       # server answered
    assert plan.count("kv.request",
                      f"127.0.0.1:{client.port}") == 1  # no retry


def test_kv_delay_fault_slows_but_succeeds(kv_world):
    _, client = kv_world
    fl.install(fl.FaultPlan(
        [fl.FaultSpec("delay-kv", step=0, param=0.1)]))
    t0 = time.monotonic()
    client.put("s", "k", b"v")              # the delay lands here
    assert time.monotonic() - t0 >= 0.1
    assert client.get("s", "k") == b"v"     # ...and nothing broke
    assert fl.active_plan().exhausted()


def test_kv_backoff_is_capped_and_jittered(monkeypatch):
    from horovod_tpu.runner.http_server import KVStoreClient
    monkeypatch.setenv("HVD_KV_RETRY_MAX", "5")
    monkeypatch.setenv("HVD_KV_RETRY_BASE_MS", "8")
    monkeypatch.setenv("HVD_KV_RETRY_CAP_MS", "20")
    client = KVStoreClient("127.0.0.1", 1)
    for attempt in range(1, 8):
        d = client._retry_backoff_s(attempt)
        assert 0.004 <= d <= 0.020  # jitter in [0.5, 1) x capped base


# -- replica.route injection point -------------------------------------------

def test_route_kill_rank_fault_kills_named_replica_and_fails_over():
    server, sched, port = _two_replica_server()
    try:
        fl.install(fl.FaultPlan(
            [fl.FaultSpec("kill-rank", point="replica.route", step=0,
                          target="replica-0")]))
        out = _post(port, {"tokens": [2, 3]})  # triggers + fails over
        assert out["replica"] == "replica-1"
        assert sched.healthz()["status"] == "degraded"
        assert [r["state"] for r in sched.healthz()["replicas"]] == \
            ["dead", "healthy"]
    finally:
        server.stop()


# -- preempt.poll injection point (sentinel marker publication) --------------

def test_sentinel_publishes_and_clears_marker_under_kill_rank_fault(
        kv_world, monkeypatch):
    from horovod_tpu.elastic.preemption import (PREEMPT_SCOPE,
                                                PreemptionSentinel)
    _, client = kv_world
    # Unreachable metadata endpoint: with a plan installed the sentinel
    # reads that as "NONE", so the post-fault clear path works hermetically.
    monkeypatch.setenv("HVD_TPU_MAINTENANCE_URL",
                       "http://127.0.0.1:9/never")
    plan = fl.install(fl.FaultPlan(
        [fl.FaultSpec("kill-rank", step=2, target="chaos-host",
                      repeat=2)]))
    sentinel = PreemptionSentinel(client, hostname="chaos-host",
                                  poll_interval_s=0.01)
    for _ in range(2):
        sentinel.step()                       # steps 0-1: no fault
    assert client.scan(PREEMPT_SCOPE) == {}
    sentinel.step()                           # step 2: fault fires
    assert client.scan(PREEMPT_SCOPE) == {"chaos-host": b"FAULTLINE_PREEMPT"}
    sentinel.step()                           # step 3: still in window
    sentinel.step()                           # step 4: window over -> clear
    assert client.scan(PREEMPT_SCOPE) == {}
    assert plan.firing_sequence() == [("preempt.poll", 2, "kill-rank"),
                                      ("preempt.poll", 3, "kill-rank")]
