"""runner/http_server.py concurrent-waiter coverage (ISSUE 4 satellite).

The Python KV server becomes the serving control plane's fallback
(HVD_TPU_KV_SERVER=python; serve/replica.py polls the ``preempt`` scope
through it), and its waiter machinery — the per-scope conditions behind
``_cond``/``_notify``, the ``_put_wait`` announce-then-await fold, and the
``_gc_cond`` delete-while-waiting path — had no dedicated concurrency
test.  Every test here forces the PYTHON backend explicitly: the native
C++ server has its own test coverage and none of these code paths.
"""

import base64
import threading
import time

import pytest

from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer


@pytest.fixture()
def py_kv(monkeypatch):
    """A running PYTHON-backend KV server + a client factory."""
    monkeypatch.setenv("HVD_TPU_KV_SERVER", "python")
    server = KVStoreServer()
    port = server.start(0)
    assert server.httpd is not None  # really the Python backend
    yield server, (lambda: KVStoreClient("127.0.0.1", port))
    server.stop()


def test_long_poll_wakes_only_its_scope(py_kv):
    """A PUT must wake ITS scope's waiters promptly while waiters on other
    scopes sleep out their windows untouched (the per-scope-condition
    design in _cond's docstring — one global condition would wake all)."""
    server, mk_client = py_kv
    n_scopes = 8
    results, latencies = {}, {}
    barrier = threading.Barrier(n_scopes + 1)

    def waiter(i):
        c = mk_client()
        barrier.wait()
        t0 = time.monotonic()
        out = c.get(f"scope{i}", "key", wait=10.0)
        latencies[i] = time.monotonic() - t0
        results[i] = out

    threads = [threading.Thread(target=waiter, args=(i,))
               for i in range(n_scopes)]
    for t in threads:
        t.start()
    barrier.wait()
    time.sleep(0.1)  # let every waiter park on its condition
    writer = mk_client()
    for i in range(n_scopes):
        writer.put(f"scope{i}", "key", f"v{i}".encode())
    for t in threads:
        t.join(timeout=30)
    assert results == {i: f"v{i}".encode() for i in range(n_scopes)}
    assert all(lat < 8.0 for lat in latencies.values()), latencies


def test_put_wait_fanout_all_waiters_get_verdict(py_kv):
    """The negotiation pattern at scale: N workers fold announce+await
    into one put_wait each; the coordinator collects all N announcements
    with a min-keys scan long-poll, then publishes ONE verdict that must
    release every parked put_wait."""
    server, mk_client = py_kv
    n = 16
    verdicts = [None] * n

    def worker(i):
        c = mk_client()
        verdicts[i] = c.put_wait("requests", f"rank{i}",
                                 f"req{i}".encode(),
                                 "verdicts", "round0", wait=20.0)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    coordinator = mk_client()
    announced = coordinator.scan("requests", wait=20.0, min_keys=n)
    assert len(announced) == n  # min-keys long-poll saw every announce
    assert announced["rank3"] == b"req3"
    coordinator.put("verdicts", "round0", b"APPROVED")
    for t in threads:
        t.join(timeout=30)
    assert verdicts == [b"APPROVED"] * n


def test_scope_delete_wakes_waiters_who_reissue(py_kv):
    """_gc_cond contract: deleting a scope must WAKE its parked waiters
    (they re-check, time out their chunk, re-issue) — and a key published
    AFTER the delete (on the scope's fresh condition) must still reach a
    re-issued waiter instead of stranding it on the popped condition."""
    server, mk_client = py_kv
    got = []

    def waiter():
        c = mk_client()
        # First long-poll chunk may be cut short by the delete (404);
        # the client re-issues like the real negotiation loop does.
        for _ in range(20):
            out = c.get("doomed", "answer", wait=1.0)
            if out is not None:
                got.append(out)
                return

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    admin = mk_client()
    admin.put("doomed", "other_key", b"x")  # materialize the scope
    admin.delete_scope("doomed")            # pops scope AND its condition
    time.sleep(0.1)
    admin.put("doomed", "answer", b"42")    # NEW condition, same name
    t.join(timeout=30)
    assert got == [b"42"]


def test_put_wait_timeout_returns_none_but_stores_value(py_kv):
    server, mk_client = py_kv
    c = mk_client()
    t0 = time.monotonic()
    out = c.put_wait("announce", "k", b"payload", "never", "coming",
                     wait=0.3)
    assert out is None
    assert time.monotonic() - t0 < 5.0
    assert c.get("announce", "k") == b"payload"  # the put half landed


def test_concurrent_mixed_load_no_lost_updates(py_kv):
    """Thundering-herd smoke: concurrent batch-puts, long-poll gets and
    scans across shared scopes — every writer's full payload must be
    readable afterwards and no thread may wedge (the cache_lock +
    per-scope-condition invariants under real thread interleaving)."""
    server, mk_client = py_kv
    n_writers, n_keys = 8, 25
    errors = []

    def writer(w):
        try:
            c = mk_client()
            c.put_batch(f"bulk{w % 4}",
                        {f"w{w}k{k}": f"{w}:{k}".encode()
                         for k in range(n_keys)})
        except Exception as e:  # pragma: no cover - diagnostic
            errors.append(repr(e))

    def poller(w):
        try:
            c = mk_client()
            out = c.get(f"bulk{w % 4}", f"w{w}k0", wait=15.0)
            if out != f"{w}:0".encode():
                errors.append(f"poller {w} got {out!r}")
        except Exception as e:  # pragma: no cover - diagnostic
            errors.append(repr(e))

    threads = [threading.Thread(target=poller, args=(w,))
               for w in range(n_writers)]
    threads += [threading.Thread(target=writer, args=(w,))
                for w in range(n_writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    c = mk_client()
    for w in range(n_writers):
        scope = c.scan(f"bulk{w % 4}")
        for k in range(n_keys):
            assert scope[f"w{w}k{k}"] == f"{w}:{k}".encode()


def test_server_side_put_does_notify_waiters(py_kv):
    """KVStoreServer.put (the launcher's in-process write path) must wake
    HTTP long-pollers — the rendezvous publishes the host plan this way
    while workers long-poll for it."""
    server, mk_client = py_kv
    out = {}

    def waiter():
        out["v"] = mk_client().get("rendezvous", "rank/0", wait=10.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    server.put("rendezvous", "rank/0", b"slotinfo")
    t.join(timeout=30)
    assert out["v"] == b"slotinfo"
