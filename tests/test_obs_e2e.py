"""hvdtrace end-to-end acceptance (ISSUE 9): a 4-replica process-set
world under a sampled concurrent storm with a failover mid-flight, then
the fleet merge.

Pins the acceptance properties in one scenario:

(a) ``hvdtrace`` merge of the shards produces a VALID Chrome-trace JSON
    whose event timestamps are globally monotonic;
(b) a failed-over request's span tree CROSSES replicas with correct
    parentage: queue-wait/prefill spans on the dead replica, a
    resubmission span + decode on the survivor, all children of the one
    http-handle root;
(c) ``/metrics`` exposes the per-stage ``hvd_serve_stage_ms``
    histograms, and a request's stage sums equal its end-to-end latency
    (the exact-partition contract);
(d) the rendezvous-KV clock-anchor path attaches an RTT skew bound to
    the merge.
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd  # noqa: F401 - world fixture
from horovod_tpu.elastic.preemption import PREEMPT_SCOPE
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.obs import merge as mg
from horovod_tpu.obs import tracing as tr
from horovod_tpu.obs.cli import run_commandline as hvdtrace_cli
from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer
from horovod_tpu.serve import ServeServer, TransformerAdapter, build_replicas

# Serialize with the other heavy e2e files (conftest loadgroup policy).
pytestmark = pytest.mark.xdist_group("heavy_e2e")

CFG = TransformerConfig(vocab_size=89, num_layers=2, num_heads=2,
                        d_model=32, d_ff=64, max_len=96, causal=True,
                        dtype=jnp.float32, scan_layers=False)
NEW_TOKENS = 12
N_REQUESTS = 48


def _gen(port, prompt, n=NEW_TOKENS, timeout=120):
    body = json.dumps({"tokens": prompt, "max_new_tokens": n}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.loads(resp.read())
        out["trace_id"] = resp.headers.get("X-Trace-Id")
        return out


def _metric_lines(port, name):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=30) as resp:
        text = resp.read().decode()
    return [l for l in text.splitlines() if l.startswith(name)]


@pytest.mark.slow  # ~25s storm; unit-level merge coverage lives in test_obs.py
def test_traced_storm_with_failover_merges_across_replicas(
        hvd8, tmp_path):
    shard_dir = tmp_path / "shards"
    tracer = tr.install(tr.Tracer(sample=1.0, shard_dir=str(shard_dir)))
    model = Transformer(CFG)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    sched = build_replicas(lambda: TransformerAdapter(CFG, params),
                           num_replicas=4, max_batch=4)
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    kv = KVStoreServer()
    kv_port = kv.start(0)
    merged_path = tmp_path / "fleet.json"
    try:
        client = KVStoreClient("127.0.0.1", kv_port)
        tr.publish_clock_anchor(client, "serve-world")
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, CFG.vocab_size,
                               size=(int(rng.randint(3, 24)),)).tolist()
                   for _ in range(N_REQUESTS)]
        _gen(port, prompts[0])  # warm one bucket

        victim = sched.replicas[0]
        sched.watch_preemption(client,
                               {"preempt-host": list(victim.ranks)},
                               poll_s=0.05)
        results = [None] * N_REQUESTS
        errors = []

        def run(i):
            try:
                results[i] = _gen(port, prompts[i])
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(N_REQUESTS)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        while victim.engine.active_count == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert victim.engine.active_count > 0, "victim never got load"
        client.put(PREEMPT_SCOPE, "preempt-host",
                   b"TERMINATE_ON_HOST_MAINTENANCE")
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        requeued = [r for r in results if r["requeues"] > 0]
        assert requeued, "no request failed over mid-flight"
        assert all(r["trace_id"] for r in results)  # sample=1: all traced

        # (c) stage histograms on /metrics, retry stage populated by the
        # failed-over requests.
        assert _metric_lines(port, "hvd_serve_stage_ms_bucket")
        retry_count = float(_metric_lines(
            port, 'hvd_serve_stage_ms_count{stage="retry"}'
        )[0].split()[-1])
        assert retry_count >= len(requeued)

        # (c) exact-partition: one fresh request served alone — its
        # stage sums equal its end-to-end latency.
        from horovod_tpu.serve import Request
        probe = Request(prompts[0], max_new_tokens=NEW_TOKENS)
        sched.submit(probe)
        probe.result(timeout=120)
        e2e_ms = (time.monotonic() - probe.submitted_at) * 1e3
        total = sum(probe.stage_ms.values())
        assert 0 < total <= e2e_ms + 1e-6
        assert total >= e2e_ms - 50  # result() wakeup slack only
    finally:
        server.stop()
        kv.stop()

    # -- the fleet merge (tracer closed so shards are flushed) ---------------
    tr.uninstall()
    rc = hvdtrace_cli(["--dir", str(shard_dir), "-o", str(merged_path),
                       "--kv", f"127.0.0.1:{kv_port}"])
    # KV already stopped: the CLI falls back to shard anchors, still rc 0.
    assert rc == 0

    # (a) valid Chrome-trace JSON, globally monotonic timestamps.
    events = json.load(open(merged_path))
    assert all("ph" in e and "name" in e for e in events)
    ts = [e["ts"] for e in events if "ts" in e]
    assert ts and ts == sorted(ts)
    # All four replicas plus the server contributed shards.
    proc_names = {e["args"]["name"] for e in events
                  if e["name"] == "process_name"}
    assert "server" in proc_names
    assert sum(1 for p in proc_names if p.startswith("replica-")) >= 2

    # (b) the failed-over request's span tree crosses replicas with a
    # resubmission span and correct parentage.
    shards = mg.load_shards(str(shard_dir))
    traces = mg.spans_by_trace(shards)
    crossing = None
    for r in requeued:
        spans = [e for e in traces.get(r["trace_id"], [])
                 if e["type"] == "span"]
        procs = {s["proc"] for s in spans
                 if s["proc"].startswith("replica-")}
        if len(procs) >= 2 and any(s["name"] == "resubmission"
                                   for s in spans):
            crossing = (r, spans, procs)
            break
    assert crossing is not None, \
        f"no requeued trace crossed replicas: {requeued}"
    r, spans, procs = crossing
    root = next(s for s in spans if s["name"] == "http-handle")
    resub = next(s for s in spans if s["name"] == "resubmission")
    assert resub["parent"] == root["span"]  # child of the request root
    assert resub["proc"] == r["replica"]    # attributed to the survivor
    assert r["replica"] in procs and len(procs) >= 2
    # Every span in the tree resolves to the root.
    by_id = {s["span"]: s for s in spans}
    for s in spans:
        node = s
        hops = 0
        while node["parent"] is not None and hops < 10:
            node = by_id.get(node["parent"], root)
            hops += 1
        assert node is root
    # The merged tree's timestamps are monotonic parent→child.
    tree = mg.build_tree(spans)
    assert len(tree) == 1 and tree[0]["name"] == "http-handle"

    def check(node):
        for c in node["children"]:
            if "wall0_ns" in c and "wall0_ns" in node:
                assert c["wall0_ns"] >= node["wall0_ns"]
            check(c)
    check(tree[0])

    # (d) per-request critical path: the failed-over request shows
    # retry time and both replicas.
    cp = mg.critical_path(traces[r["trace_id"]])
    assert cp["resubmissions"] >= 1
    assert cp["stages_ms"]["retry"] > 0
    assert len(cp["replicas"]) >= 2
    assert cp["total_ms"] > 0


def test_kv_anchor_refinement_attaches_skew_bound(hvd8, tmp_path):
    """The rendezvous-KV clock path end-to-end: anchors published
    through a live KV attach RTT bounds to the merged shards."""
    shard_dir = tmp_path / "shards"
    tracer = tr.install(tr.Tracer(sample=1.0, shard_dir=str(shard_dir)))
    kv = KVStoreServer()
    kv_port = kv.start(0)
    try:
        client = KVStoreClient("127.0.0.1", kv_port)
        tr.publish_clock_anchor(client, "world")
        ctx = tracer.new_context()
        t0 = time.monotonic()
        tracer.emit_span(ctx, "http-handle", t0, t0 + 0.01, "server",
                         root=True)
        tr.uninstall()
        shards = mg.load_shards(str(shard_dir))
        mg.apply_kv_anchors(shards, mg.kv_anchors(client))
        assert all(s.rtt_ns is not None and s.rtt_ns > 0
                   for s in shards)
        _, meta = mg.merge_chrome(shards)
        assert all(s["skew_bound_ns"] > 0 for s in meta["shards"])
    finally:
        tr.uninstall()
        kv.stop()
