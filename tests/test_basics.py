"""Init/info API tests (reference: test/parallel/test_common.py and the
horovod_rank/size C-API surface, operations.cc:932-1405)."""

import jax
import pytest

import horovod_tpu as hvd


def test_init_idempotent(hvd8):
    assert hvd8.is_initialized()
    hvd8.init()  # second call is a no-op
    assert hvd8.is_initialized()


def test_rank_size(hvd8):
    assert hvd8.size() == 8
    assert hvd8.rank() == 0
    assert hvd8.local_size() == 8
    assert hvd8.local_rank() == 0
    assert hvd8.cross_size() == 1
    assert hvd8.cross_rank() == 0
    assert hvd8.num_slots() == 8
    assert hvd8.is_homogeneous()


def test_uninitialized_raises():
    hvd.shutdown()
    with pytest.raises(ValueError, match="initialized"):
        hvd.rank()


def test_mesh(hvd8):
    mesh = hvd8.mesh()
    assert mesh.shape[hvd8.mesh_axis()] == 8
    assert hvd8.mesh_axis() == "hvd"


def test_built_queries(hvd8):
    assert hvd8.xla_built() and hvd8.xla_enabled()
    assert not hvd8.mpi_built() and not hvd8.mpi_enabled()
    assert not hvd8.nccl_built()
    assert not hvd8.gloo_built()
    assert not hvd8.cuda_built()
    assert not hvd8.mpi_threads_supported()


def test_process_set_crud(hvd8):
    ps = hvd.add_process_set([0, 1, 2])
    assert ps.process_set_id is not None and ps.process_set_id > 0
    assert ps.size() == 3
    assert ps.rank() == 0  # process rank 0 is member 0
    assert ps.included()
    # Identical set returns the existing registration (operations.cc:1262).
    ps2 = hvd.add_process_set([2, 1, 0])
    assert ps2.process_set_id == ps.process_set_id
    ids = hvd.get_process_set_ids()
    assert 0 in ids and ps.process_set_id in ids
    assert hvd.remove_process_set(ps)
    assert ps.process_set_id not in hvd.get_process_set_ids()


def test_global_process_set_protected(hvd8):
    assert not hvd.remove_process_set(hvd.global_process_set)


def test_process_set_excluded_rank(hvd8):
    ps = hvd.ProcessSet([3, 4])
    hvd.add_process_set(ps)
    assert ps.rank() is None  # process rank 0 not a member
    assert not ps.included()
    assert ps.members() == (3, 4)
    hvd.remove_process_set(ps)


def test_process_set_validation(hvd8):
    with pytest.raises(ValueError):
        hvd.add_process_set([0, 99])
    with pytest.raises(ValueError):
        hvd.add_process_set([])
