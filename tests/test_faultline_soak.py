"""Chaos soak (ISSUE 6 acceptance, ``slow``): a 4-replica world under a
seeded 3-fault plan — rank kill (via the REAL sentinel/marker/watcher
machinery), KV transport flakes, and a poisoned engine step — must
converge back to ``healthz: ok`` with every accepted request answered
correctly, including at least one replica re-admitted via ``mark_alive``
after its "rank" recovers.

The fault sequence is a pure function of ``HVD_FAULTLINE_SEED``
(tests/test_faultline.py pins schedule/firing determinism in isolation;
here the same contract is asserted on the live plan's schedule), so a
failing soak reproduces exactly by re-running with the same seed.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import faultline as fl
from horovod_tpu.elastic.preemption import PreemptionSentinel
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer
from horovod_tpu.serve import ServeServer, TransformerAdapter, build_replicas

pytestmark = [pytest.mark.slow, pytest.mark.xdist_group("heavy_e2e")]

CFG = TransformerConfig(vocab_size=89, num_layers=2, num_heads=2,
                        d_model=32, d_ff=64, max_len=96, causal=True,
                        dtype=jnp.float32, scan_layers=False)
NEW_TOKENS = 24
N_REQUESTS = 64
SEED = 1234


def _gen(port, prompt, n=NEW_TOKENS, timeout=180):
    body = json.dumps({"tokens": prompt, "max_new_tokens": n}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _gen_with_retry(port, prompt):
    """A chaos client: 5xx (a poisoned batch surfaces as 500, a no-
    survivor window as 503) is retried — the fault costs latency, never a
    lost or wrong answer.  4xx would re-raise (nothing here sends any)."""
    last = None
    for _ in range(6):
        try:
            return _gen(port, prompt)
        except urllib.error.HTTPError as e:
            if e.code < 500:
                raise
            last = e
            time.sleep(0.25)
    raise AssertionError(f"request never recovered: {last}")


def _specs():
    return [
        # Fires through the sentinel's poll: marker published, watcher
        # kills the replica, window ends, marker clears, watcher revives.
        # Early step + fast polls: the kill must land ~0.1 s after the
        # sentinel lights up, while the storm is still in flight.
        fl.FaultSpec("kill-rank", target="chaos-host", step=2, repeat=8),
        # A 2-drop train against the control plane: inside the KV
        # client's default 3-attempt retry budget, so the watcher and
        # sentinel ride it out.
        fl.FaultSpec("drop-kv-response", step=3, repeat=2),
        # One poisoned iteration on a survivor replica mid-storm.
        fl.FaultSpec("poison-step", target="replica-1", step=40),
    ]


def test_chaos_soak_converges_to_ok_with_no_lost_or_wrong_answers(
        hvd8, monkeypatch):
    # Hermetic chaos world: no metadata server (the sentinel reads the
    # unreachable endpoint as NONE while a plan is installed).
    monkeypatch.setenv("HVD_TPU_MAINTENANCE_URL", "http://127.0.0.1:9/x")
    model = Transformer(CFG)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    sched = build_replicas(lambda: TransformerAdapter(CFG, params),
                           num_replicas=4, max_batch=4)
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    kv = KVStoreServer()
    kv_port = kv.start(0)
    client = KVStoreClient("127.0.0.1", kv_port)
    victim = sched.replicas[0]
    sentinel = None
    try:
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, CFG.vocab_size,
                               size=(int(rng.randint(3, 24)),)).tolist()
                   for _ in range(N_REQUESTS)]
        # Fault-free reference pass (also warms every prefill bucket).
        singles = {tuple(p): _gen(port, p)["tokens"] for p in prompts[:8]}

        sched.watch_preemption(client, {"chaos-host": list(victim.ranks)},
                               poll_s=0.03)
        plan = fl.install(fl.FaultPlan(_specs(), seed=SEED))
        # Reproducibility contract on the LIVE plan: the schedule is a
        # pure function of (seed, specs).
        assert plan.schedule() == fl.FaultPlan(_specs(),
                                               seed=SEED).schedule()

        # Storm first, then light the sentinel: its poll counter starts
        # at 0, so the kill window (steps 2..9 at 0.03 s/poll) lands
        # ~0.1 s in — while the storm is demonstrably in flight.
        results = [None] * N_REQUESTS
        errors = []

        def run(i):
            try:
                results[i] = _gen_with_retry(port, prompts[i])
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(N_REQUESTS)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        while victim.engine.active_count == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert victim.engine.active_count > 0, "victim never got load"
        sentinel = PreemptionSentinel(client, hostname="chaos-host",
                                      poll_interval_s=0.03)
        sentinel.start()

        for t in threads:
            t.join(timeout=300)
        assert not errors, errors

        # Every fault in the plan fired (the poison step needs the
        # engine's iteration counter to reach it; wait it out).
        deadline = time.monotonic() + 60
        while not plan.exhausted() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert plan.exhausted(), plan.schedule()
        fired_kinds = {k for _, _, k in plan.firing_sequence()}
        assert fired_kinds == {"kill-rank", "drop-kv-response",
                               "poison-step"}

        # CONVERGENCE: the marker cleared and the watcher re-admitted the
        # victim — back to healthz ok with all 4 replicas healthy.
        deadline = time.monotonic() + 60
        health = None
        while time.monotonic() < deadline:
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30).read())
            if health["status"] == "ok" and health["healthy"] == 4:
                break
            time.sleep(0.1)
        assert health["status"] == "ok" and health["healthy"] == 4, health

        # The fleet really went DOWN and CAME BACK (not "nothing
        # happened"): one mark_dead, one mark_alive, requeued work.
        snap = sched.metrics.snapshot()
        assert snap["replica_events"]["mark_dead"] >= 1
        assert snap["replica_events"]["mark_alive"] >= 1
        assert snap["requests"]["requeued"] >= 1, snap["requests"]

        # ZERO lost or wrong answers: every one of the 48 accepted
        # requests matches its single-served reference — including work
        # requeued off the dead replica and retries after the poison.
        for p, r in zip(prompts, results):
            key = tuple(p)
            if key not in singles:
                singles[key] = _gen(port, p)["tokens"]
            assert r["tokens"] == singles[key], (p, r)

        # The revived replica is genuinely serving again.
        probe = _gen(port, prompts[0])
        assert probe["tokens"] == singles[tuple(prompts[0])]
        deadline = time.monotonic() + 30
        served_by_victim = False
        while not served_by_victim and time.monotonic() < deadline:
            out = _gen(port, prompts[1])
            assert out["tokens"] == singles[tuple(prompts[1])]
            served_by_victim = out["replica"] == victim.replica_id
        assert served_by_victim, "revived replica never took traffic"
    finally:
        if sentinel is not None:
            sentinel.stop()
        fl.uninstall()
        server.stop()
        kv.stop()
