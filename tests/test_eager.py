"""Eager (op-by-op) API tests in emulated-rank mode.

The eager path is the analog of the reference's enqueue→negotiate→execute
pipeline (torch/mpi_ops.py surface tested by test/parallel/test_torch.py);
here tensors are stacked per-rank values [N, ...] (tests/conftest.py) and the
engine shard_maps the collective over the 8 virtual devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd

N = 8


@pytest.fixture()
def stacked():
    rng = np.random.RandomState(7)
    return jnp.asarray(rng.randn(N, 5, 2).astype(np.float32))


def test_eager_allreduce_average(hvd8, stacked):
    out = hvd8.allreduce(stacked)
    expected = np.mean(np.asarray(stacked), axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)


def test_eager_allreduce_sum_op(hvd8, stacked):
    out = hvd8.allreduce(stacked, op=hvd.Sum)
    np.testing.assert_allclose(out[0], np.sum(np.asarray(stacked), 0),
                               rtol=1e-5)


def test_eager_allreduce_average_deprecated_flag(hvd8, stacked):
    with pytest.warns(DeprecationWarning):
        out = hvd8.allreduce(stacked, average=True)
    np.testing.assert_allclose(out[0], np.mean(np.asarray(stacked), 0),
                               rtol=1e-5)
    with pytest.raises(ValueError):
        hvd8.allreduce(stacked, average=True, op=hvd.Sum)


def test_eager_allreduce_compression(hvd8, stacked):
    out = hvd8.allreduce(stacked, compression=hvd.Compression.fp16)
    assert out.dtype == jnp.float32  # decompressed back
    np.testing.assert_allclose(out[0], np.mean(np.asarray(stacked), 0),
                               rtol=5e-2, atol=1e-3)
    out = hvd8.allreduce(stacked, compression=hvd.Compression.bf16)
    np.testing.assert_allclose(out[0], np.mean(np.asarray(stacked), 0),
                               rtol=5e-2, atol=1e-2)


def test_eager_allreduce_process_set(hvd8, stacked):
    ps = hvd.add_process_set([0, 1])
    out = hvd8.allreduce(stacked, process_set=ps)
    arr = np.asarray(stacked)
    np.testing.assert_allclose(out[0], arr[:2].mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(out[5], arr[5], rtol=1e-6)
    hvd.remove_process_set(ps)


def test_eager_async_poll_synchronize(hvd8, stacked):
    h = hvd8.allreduce_async(stacked, op=hvd.Sum)
    assert isinstance(h, int)
    out = hvd8.synchronize(h)
    np.testing.assert_allclose(out[0], np.sum(np.asarray(stacked), 0),
                               rtol=1e-5)
    with pytest.raises(ValueError):
        hvd8.synchronize(h)  # handle consumed


def test_eager_poll_eventually_true(hvd8, stacked):
    h = hvd8.allreduce_async(stacked)
    out = hvd8.synchronize(h)
    jax.block_until_ready(out)


def test_eager_grouped_allreduce(hvd8):
    rng = np.random.RandomState(1)
    a = jnp.asarray(rng.randn(N, 3).astype(np.float32))
    b = jnp.asarray(rng.randn(N, 2, 2).astype(np.float32))
    oa, ob = hvd8.grouped_allreduce([a, b], op=hvd.Average)
    np.testing.assert_allclose(oa[0], np.mean(np.asarray(a), 0), rtol=1e-5)
    np.testing.assert_allclose(ob[0], np.mean(np.asarray(b), 0), rtol=1e-5)
    h = hvd8.grouped_allreduce_async([a, b], op=hvd.Sum)
    oa, ob = hvd8.synchronize(h)
    np.testing.assert_allclose(oa[0], np.sum(np.asarray(a), 0), rtol=1e-5)


def test_eager_allgather(hvd8, stacked):
    out = hvd8.allgather(stacked)
    expected = np.asarray(stacked).reshape(N * 5, 2)
    np.testing.assert_allclose(out[0], expected, rtol=1e-6)


def test_eager_broadcast(hvd8, stacked):
    out = hvd8.broadcast(stacked, root_rank=3)
    for r in range(N):
        np.testing.assert_allclose(out[r], np.asarray(stacked)[3], rtol=1e-6)


def test_eager_alltoall_equal(hvd8):
    x = jnp.asarray(np.arange(N * N).reshape(N, N, 1).astype(np.float32))
    out = hvd8.alltoall(x)
    arr = np.asarray(x)
    expected0 = np.stack([arr[s, 0] for s in range(N)], axis=0)
    np.testing.assert_allclose(out[0], expected0, rtol=1e-6)


def test_eager_alltoallv_splits(hvd8):
    # rank r sends r rows to each receiver... use simple per-rank splits.
    rng = np.random.RandomState(5)
    splits = rng.randint(0, 3, size=(N, N))
    tensors = [jnp.asarray(rng.randn(int(splits[r].sum()), 2)
                           .astype(np.float32)) for r in range(N)]
    outputs, received = hvd8.alltoall(tensors, splits=jnp.asarray(splits))
    received = np.asarray(received)
    np.testing.assert_array_equal(received, splits.T)
    # verify content for receiver 2
    offsets = np.concatenate(
        [np.zeros((N, 1), np.int64), np.cumsum(splits, axis=1)], axis=1)
    expected = np.concatenate(
        [np.asarray(tensors[s])[offsets[s, 2]:offsets[s, 3]]
         for s in range(N)], axis=0)
    np.testing.assert_allclose(np.asarray(outputs[2]), expected, rtol=1e-6)


def test_eager_reducescatter(hvd8):
    x = jnp.asarray(np.random.RandomState(9).randn(N, 16, 2)
                    .astype(np.float32))
    out = hvd8.reducescatter(x, op=hvd.Sum)
    total = np.sum(np.asarray(x), axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], total[r * 2:(r + 1) * 2],
                                   rtol=1e-5)


def test_eager_barrier_and_join(hvd8):
    hvd8.barrier()  # must not deadlock or raise
    assert hvd8.join() == N - 1


def test_eager_replicated_input_unstacked_output(hvd8):
    # Leading dim != 8 → treated as "same value on every rank"
    # (broadcast_variables idiom); uniform-output ops return it unstacked.
    x = jnp.asarray(np.random.RandomState(3).randn(3, 2).astype(np.float32))
    out = hvd8.allreduce(x, op=hvd.Sum)
    assert out.shape == x.shape
    np.testing.assert_allclose(out, 8 * np.asarray(x), rtol=1e-5)
    out = hvd8.broadcast(x, root_rank=4)
    np.testing.assert_allclose(out, np.asarray(x), rtol=1e-6)


def test_exec_cache_reuse(hvd8, stacked):
    eng = hvd8.ops._engine()
    before = len(eng._exec_cache)
    hvd8.allreduce(stacked)
    mid = len(eng._exec_cache)
    hvd8.allreduce(stacked)
    assert len(eng._exec_cache) == mid
    assert mid >= before
