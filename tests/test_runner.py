"""Launcher tests (reference: test/single/test_run.py — flag parsing, env
mapping, host assignment — and test/integration/test_static_run.py which
invokes the real CLI on localhost)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu import config as hvd_config
from horovod_tpu.runner import hosts as H
from horovod_tpu.runner.launch import parse_args, env_from_args
from horovod_tpu.runner.http_server import (
    KVStoreClient, KVStoreServer, RendezvousServer)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Serialize with the other subprocess-world e2e files (conftest
# pytest_collection_modifyitems): overlapping multi-process worlds on one
# host core cascade spurious stall timeouts.
pytestmark = pytest.mark.xdist_group("heavy_e2e")


# -- flag parsing / env mapping (test_run.py flag matrix) --------------------

def test_parse_args_basic():
    args = parse_args(["-np", "4", "-H", "h1:2,h2:2", "--verbose",
                       "python", "train.py"])
    assert args.np == 4
    assert args.hosts == "h1:2,h2:2"
    assert args.verbose
    assert args.command == ["python", "train.py"]


def test_env_from_args_knobs():
    args = parse_args([
        "-np", "2",
        "--fusion-threshold-mb", "64",
        "--cycle-time-ms", "0.5",
        "--cache-capacity", "2048",
        "--hierarchical-allreduce",
        "--autotune", "--autotune-log-file", "/tmp/at.log",
        "--timeline-filename", "/tmp/tl.json", "--timeline-mark-cycles",
        "--no-stall-check",
        "--stall-check-warning-time-seconds", "30",
        "--log-level", "DEBUG",
        "python", "x.py"])
    env = env_from_args(args)
    assert env[hvd_config.HOROVOD_FUSION_THRESHOLD] == str(64 * 1024 * 1024)
    assert env[hvd_config.HOROVOD_CYCLE_TIME] == "0.5"
    assert env[hvd_config.HOROVOD_CACHE_CAPACITY] == "2048"
    assert env[hvd_config.HOROVOD_HIERARCHICAL_ALLREDUCE] == "1"
    assert env[hvd_config.HOROVOD_AUTOTUNE] == "1"
    assert env[hvd_config.HOROVOD_AUTOTUNE_LOG] == "/tmp/at.log"
    assert env[hvd_config.HOROVOD_TIMELINE] == "/tmp/tl.json"
    assert env[hvd_config.HOROVOD_TIMELINE_MARK_CYCLES] == "1"
    assert env[hvd_config.HOROVOD_STALL_CHECK_DISABLE] == "1"
    assert env[hvd_config.HOROVOD_STALL_CHECK_TIME_SECONDS] == "30"
    assert env[hvd_config.HOROVOD_LOG_LEVEL] == "debug"


def test_disable_cache_flag():
    args = parse_args(["-np", "1", "--disable-cache", "python", "x.py"])
    assert env_from_args(args)[hvd_config.HOROVOD_CACHE_CAPACITY] == "0"


def test_config_file_with_cli_precedence(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(textwrap.dedent("""
        params:
          fusion-threshold-mb: 32
          cache-capacity: 512
        logging:
          log-level: INFO
    """))
    # CLI flag --cache-capacity must beat the config file value.
    args = parse_args(["-np", "1", "--config-file", str(cfg),
                       "--cache-capacity", "4096", "python", "x.py"])
    env = env_from_args(args)
    assert env[hvd_config.HOROVOD_FUSION_THRESHOLD] == str(32 * 1024 * 1024)
    assert env[hvd_config.HOROVOD_CACHE_CAPACITY] == "4096"
    assert env[hvd_config.HOROVOD_LOG_LEVEL] == "info"


def test_gloo_mpi_flags_mutually_exclusive():
    with pytest.raises(SystemExit):
        parse_args(["-np", "1", "--gloo", "--mpi", "python", "x.py"])


def test_mpi_gloo_noop_flags_warn(capsys):
    """--mpi/--gloo are single-backend no-ops but must SAY so (reference
    launch.py:747 run_controller chooses a real backend; silence would
    imply mpirun semantics)."""
    parse_args(["-np", "1", "--mpi", "python", "x.py"])
    err = capsys.readouterr().err
    assert "--mpi is accepted for compatibility and ignored" in err
    assert "docs/migration.md" in err
    parse_args(["-np", "1", "--gloo", "python", "x.py"])
    err = capsys.readouterr().err
    assert "--gloo is accepted for compatibility and ignored" in err


def test_check_build_prints_matrix(capsys):
    """--check-build (reference runner/launch.py:110): the matrix answers
    from the core's built/enabled surface — one framework, one backend."""
    with pytest.raises(SystemExit) as ei:
        parse_args(["--check-build"])
    assert ei.value.code == 0
    out = capsys.readouterr().out
    assert "Available Frameworks" in out
    assert "[X] JAX" in out
    assert "[ ] PyTorch" in out
    assert "Available Controllers" in out
    assert "Available Tensor Operations" in out
    assert "[X] XLA collectives" in out
    assert "[ ] NCCL" in out


def test_jsrun_flag_outside_lsf_errors_with_pointer(capsys, monkeypatch):
    """--jsrun outside an LSF allocation must fail loudly with the
    migration pointer, not silently fall back to ssh (reference
    launch.py:764 requires LSF for jsrun)."""
    monkeypatch.delenv("LSB_JOBID", raising=False)
    with pytest.raises(SystemExit) as ei:
        parse_args(["-np", "1", "--jsrun", "python", "x.py"])
    assert ei.value.code == 2
    err = capsys.readouterr().err
    assert "requires an LSF allocation" in err
    assert "docs/migration.md" in err


def test_jsrun_flag_inside_lsf_without_jsrun_errors(capsys, monkeypatch):
    monkeypatch.setenv("LSB_JOBID", "1234")
    monkeypatch.setenv("PATH", "/nonexistent")  # no jsrun executable
    with pytest.raises(SystemExit) as ei:
        parse_args(["-np", "1", "--jsrun", "python", "x.py"])
    assert ei.value.code == 2
    assert "jsrun executable is not on PATH" in capsys.readouterr().err


# -- host assignment (hosts.py:100) -----------------------------------------

def test_parse_hosts():
    hs = H.parse_hosts("h1:2,h2:4,h3")
    assert [(h.hostname, h.slots) for h in hs] == [
        ("h1", 2), ("h2", 4), ("h3", 1)]


def test_hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text("h1 slots=2\n# comment\nh2 slots=4\n")
    hs = H.parse_host_files(str(f))
    assert [(h.hostname, h.slots) for h in hs] == [("h1", 2), ("h2", 4)]


def test_host_assignments_ranks():
    hs = H.parse_hosts("h1:2,h2:2")
    slots = H.get_host_assignments(hs, 4)
    assert [(s.rank, s.hostname, s.local_rank, s.cross_rank)
            for s in slots] == [
        (0, "h1", 0, 0), (1, "h1", 1, 0), (2, "h2", 0, 1), (3, "h2", 1, 1)]
    assert all(s.size == 4 and s.local_size == 2 and s.cross_size == 2
               for s in slots)


def test_host_assignments_oversubscribe_rejected():
    with pytest.raises(ValueError, match="slots available"):
        H.get_host_assignments(H.parse_hosts("h1:1"), 4)


def test_host_assignments_partial_use():
    slots = H.get_host_assignments(H.parse_hosts("h1:4,h2:4"), 3)
    assert len(slots) == 3
    assert slots[-1].hostname == "h1"


# -- KV store / rendezvous (http_server.py) ---------------------------------
#
# Every endpoint test runs against BOTH servers — the C++ one
# (csrc/kv_server.cc, the default) and the Python fallback — pinning wire-
# protocol parity between them.

@pytest.fixture(params=["native", "python"])
def kv_srv(request, monkeypatch):
    monkeypatch.setenv("HVD_TPU_KV_SERVER", request.param)
    srv = KVStoreServer()
    port = srv.start()
    if request.param == "native":
        # A silent fallback to Python would fake the native coverage.
        assert srv._native is not None, "native KV server failed to start"
    else:
        assert srv._native is None
    yield srv, port
    srv.stop()


def test_kvstore_put_get_roundtrip(kv_srv):
    srv, port = kv_srv
    client = KVStoreClient("127.0.0.1", port)
    client.put("scope1", "key1", b"value1")
    assert client.get("scope1", "key1") == b"value1"
    assert client.get("scope1", "missing") is None
    assert client.get("other", "key1") is None


def test_kvstore_batch_put_and_scope_delete(kv_srv):
    """Round-4 control-plane endpoints: one batch-put carries a whole
    dispatch cycle; one scope DELETE GCs a negotiation request scope."""
    srv, port = kv_srv
    client = KVStoreClient("127.0.0.1", port)
    client.put_batch("b", {"k1": b"v1", "k2": b"\x00\xffbin",
                           "sub/key": b"v3"})
    assert client.get("b", "k1") == b"v1"
    assert client.get("b", "k2") == b"\x00\xffbin"
    assert client.get("b", "sub/key") == b"v3"
    assert len(client.scan("b")) == 3
    client.delete_scope("b")
    assert client.scan("b") == {}
    client.delete_scope("b")  # idempotent on a missing scope


def test_kvstore_put_wait_roundtrip(kv_srv):
    """put_wait stores the request and holds the HTTP request until the
    awaited key exists (the one-round-trip negotiation announce+await)."""
    import threading
    import time
    srv, port = kv_srv
    client = KVStoreClient("127.0.0.1", port)
    # Timeout path: awaited key never appears -> None, request stored.
    out = client.put_wait("req", "0", b"sig", "resp_scope", "verdict",
                          wait=0.3)
    assert out is None
    assert client.get("req", "0") == b"sig"

    def publish():
        time.sleep(0.3)
        srv.put("resp_scope", "verdict", b"ok")

    threading.Thread(target=publish, daemon=True).start()
    t0 = time.time()
    out = KVStoreClient("127.0.0.1", port).put_wait(
        "req", "1", b"sig1", "resp_scope", "verdict", wait=10.0)
    assert out == b"ok"
    assert time.time() - t0 < 5.0  # woke on publish, not timeout


def test_kvstore_scan_min_keys_longpoll(kv_srv):
    """Scan with min_keys holds until the scope reaches the count (the
    coordinator's collect-all-requests primitive)."""
    import threading
    import time
    srv, port = kv_srv
    client = KVStoreClient("127.0.0.1", port)
    srv.put("rq", "0", b"a")

    def add_more():
        time.sleep(0.25)
        srv.put("rq", "1", b"b")
        srv.put("rq", "2", b"c")

    threading.Thread(target=add_more, daemon=True).start()
    out = client.scan("rq", wait=10.0, min_keys=3)
    assert set(out) == {"0", "1", "2"}
    # Timeout path returns whatever is there.
    out = client.scan("rq", wait=0.2, min_keys=99)
    assert len(out) == 3


def test_kvstore_unicode_and_escaped_names(kv_srv):
    """Tensor names are user input: quotes, backslashes, unicode, '/',
    '?', '%' must round-trip through paths, batch-put JSON, and scans."""
    srv, port = kv_srv
    client = KVStoreClient("127.0.0.1", port)
    names = ['quote"backslash\\', "unicode-é中\U0001f600",
             "query?frag#pct%20", "nested/seg/ment", "spaces and\ttabs"]
    for i, n in enumerate(names):
        client.put("esc", n, f"v{i}".encode())
    for i, n in enumerate(names):
        assert client.get("esc", n) == f"v{i}".encode()
    assert set(client.scan("esc")) == set(names)
    client.put_batch("escb", {n: b"x" for n in names})
    assert set(client.scan("escb")) == set(names)
    # Scopes take the same decoding path.
    client.put(names[1], "k", b"scoped")
    assert client.get(names[1], "k") == b"scoped"


def test_kvstore_longpoll_get_and_key_delete(kv_srv):
    """GET ?wait= long-poll wakes on PUT; DELETE of the last key GCs the
    scope (scan shows it empty)."""
    import threading
    import time
    srv, port = kv_srv
    client = KVStoreClient("127.0.0.1", port)
    assert client.get("lp", "k") is None  # immediate 404, no wait

    def put_later():
        time.sleep(0.25)
        srv.put("lp", "k", b"woken")

    threading.Thread(target=put_later, daemon=True).start()
    t0 = time.time()
    assert client.get("lp", "k", wait=10.0) == b"woken"
    assert time.time() - t0 < 5.0
    client.delete("lp", "k")
    assert client.get("lp", "k") is None
    assert client.scan("lp") == {}
    client.delete("lp", "k")  # idempotent


def test_kvstore_store_readable_after_stop(kv_srv):
    """runner.run() gathers per-rank results AFTER the launcher shuts the
    server down; both backends must keep the store readable post-stop."""
    srv, port = kv_srv
    client = KVStoreClient("127.0.0.1", port)
    client.put("runresults", "0", b"rank0-result")
    srv.stop()
    assert srv.get("runresults", "0") == b"rank0-result"
    assert srv.scan_scope("runresults") == {"0": b"rank0-result"}


def test_rendezvous_publishes_slots():
    srv = RendezvousServer()
    port = srv.start()
    try:
        slots = H.get_host_assignments(H.parse_hosts("localhost:2"), 2)
        srv.init(slots)
        client = KVStoreClient("127.0.0.1", port)
        rec = json.loads(client.get("rendezvous", "rank/1"))
        assert rec["rank"] == 1 and rec["local_rank"] == 1
        assert client.get("rendezvous", "size") == b"2"
    finally:
        srv.stop()


# -- integration: real CLI on localhost (test_static_run.py analog) ----------

WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd
hvd.init()
import jax.numpy as jnp
out = hvd.allreduce(jnp.array([float(hvd.rank()+1)]), op=hvd.Sum)
assert float(out[0]) == 3.0, out
print(f"RANK{{hvd.rank()}} OK")
"""


@pytest.mark.integration
def test_static_run_two_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO))
    env = {k: v for k, v in os.environ.items()
           if k not in ("HOROVOD_RANK", "HOROVOD_SIZE")}
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "RANK0 OK" in proc.stdout
    assert "RANK1 OK" in proc.stdout


@pytest.mark.integration
def test_static_run_failure_propagates(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0
    assert "ranks failed" in proc.stderr


@pytest.mark.integration
def test_output_filename_redirection(tmp_path):
    """--output-filename writes per-rank stdout files (reference
    --output-filename directory convention)."""
    outdir = tmp_path / "logs"
    script = tmp_path / "w.py"
    script.write_text("import os; print('hello from', os.environ['HOROVOD_RANK'])")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--output-filename", str(outdir), sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert (outdir / "rank.0" / "stdout").read_text().strip() == "hello from 0"
    assert (outdir / "rank.1" / "stdout").read_text().strip() == "hello from 1"


def test_process_set_mpi_comm_requires_mpi4py():
    from horovod_tpu.process_sets import ProcessSet
    with pytest.raises((ImportError, ValueError)):
        ProcessSet(mpi_comm=object())


PS_WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
ps = hvd.add_process_set([0])
out = hvd.allreduce(jnp.full((2,), float(hvd.rank() + 1)), op=hvd.Sum,
                    name="sub", process_set=ps)
expect = 1.0 if hvd.rank() == 0 else 2.0
assert abs(float(out[0]) - expect) < 1e-6, (hvd.rank(), out)
print(f"rank{{hvd.rank()}} PS OK")
"""


@pytest.mark.integration
def test_process_set_subset_across_processes(tmp_path):
    """Eager subset collective across real processes: member reduces over
    the set, non-member keeps its input (mask lowering end-to-end)."""
    script = tmp_path / "ps.py"
    script.write_text(PS_WORKER.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rank0 PS OK" in proc.stdout and "rank1 PS OK" in proc.stdout


@pytest.mark.integration
def test_run_api_gathers_results(tmp_path):
    """horovod_tpu.run(fn, np=2) returns per-rank results ordered by rank
    (horovod.run, runner/__init__.py:95)."""
    script = tmp_path / "runner_api.py"
    script.write_text(f"""
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {REPO!r})

def train_fn(scale):
    import jax
    jax.config.update('jax_platforms','cpu')
    import horovod_tpu as hvd, jax.numpy as jnp
    hvd.init()
    v = hvd.allreduce(jnp.array([1.0 * (hvd.rank() + 1)]), op=hvd.Sum)
    return {{"rank": hvd.rank(), "sum": float(v[0]), "scaled": scale * hvd.rank()}}

from horovod_tpu import runner
results = runner.run(train_fn, args=(10,), np=2)
assert [r["rank"] for r in results] == [0, 1], results
assert all(r["sum"] == 3.0 for r in results), results
assert results[1]["scaled"] == 10
print("RUN_API_OK")
""")
    proc = subprocess.run([sys.executable, str(script)], cwd=REPO,
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "RUN_API_OK" in proc.stdout


# ---------------------------------------------------------------------------
# Mock-exec launcher tests (reference pattern: test/single/test_run.py:1197 —
# command synthesis + env injection with execution stubbed; no ssh/pyspark
# in this image)
# ---------------------------------------------------------------------------

def test_remote_ssh_command_synthesis(monkeypatch):
    """-H with a remote host: workers launch through ssh with the HOROVOD_*
    env exported on the remote command line (gloo_run.py get_remote_command
    analog)."""
    from horovod_tpu.runner import launch as launch_mod
    calls = []

    def fake_execute(cmd, env=None, **kwargs):
        calls.append((cmd, env))
        return 0

    monkeypatch.setattr(launch_mod.safe_shell_exec, "execute", fake_execute)
    # The NIC probe would wait for registrations the fake ssh never makes.
    monkeypatch.setenv("HVD_TPU_NIC_PROBE_TIMEOUT", "0.2")
    args = launch_mod.parse_args(
        ["-np", "2", "-H", "remotebox:2", "-p", "2222",
         "python", "train.py"])
    assert launch_mod._run_static(args) == 0
    calls = [c for c in calls if "nic_probe" not in " ".join(map(str, c[0]))]
    assert len(calls) == 2
    for i, (cmd, env) in enumerate(sorted(calls, key=lambda c:
                                          c[1]["HOROVOD_RANK"])):
        assert cmd[0] == "ssh" and "remotebox" in cmd
        assert "-p" in cmd and "2222" in cmd
        remote_line = cmd[-1]
        assert f"HOROVOD_RANK={i}" in remote_line
        assert "HOROVOD_SIZE=2" in remote_line
        assert "HOROVOD_GLOO_RENDEZVOUS_ADDR=" in remote_line
        assert "python train.py" in remote_line
        assert env["HOROVOD_HOSTNAME"] == "remotebox"


def test_run_api_prefers_kv_results(monkeypatch):
    """runner.run(): per-rank results ship back through the rendezvous KV
    (runner/__init__.py:95 contract) — the temp-dir file is only a
    fallback, so remote ranks work.  Spies on the KV cache to prove the
    results really traveled through it (the fallback alone would make the
    output assertion pass)."""
    import horovod_tpu.runner as runner_mod

    orig = runner_mod._run_static
    seen = {}

    def spy(args, on_rendezvous=None):
        def cap(rdv):
            seen["server"] = rdv  # store stays readable post-stop
            if on_rendezvous is not None:
                on_rendezvous(rdv)
        return orig(args, on_rendezvous=cap)

    monkeypatch.setattr(runner_mod, "_run_static", spy)
    out = runner_mod.run(lambda: int(os.environ["HOROVOD_RANK"]) * 10, np=2)
    assert out == [0, 10]
    assert set(seen["server"].scan_scope("runresults")) == {"0", "1"}


def test_spark_run_env_injection_mocked(monkeypatch):
    """spark_integration.run with a FAKE pyspark: barrier tasks get the
    rendezvous env and per-rank results come back ordered
    (spark/runner.py:200 contract; local-Spark test pattern
    test/utils/spark_common.py:289)."""
    import sys as _sys
    import types

    captured_envs = {}

    class FakeBarrierCtx:
        def __init__(self, idx):
            self._idx = idx

        def partitionId(self):
            return self._idx

    class FakeRDD:
        def __init__(self, n):
            self.n = n

        def barrier(self):
            return self

        def mapPartitions(self, fn):
            self._fn = fn
            return self

        def collect(self):
            results = []
            base_env = dict(os.environ)
            for i in range(self.n):
                fake_pyspark.BarrierTaskContext._current = FakeBarrierCtx(i)
                os.environ.clear()
                os.environ.update(base_env)
                results.extend(self._fn(iter([i])))
                captured_envs[i] = {
                    k: v for k, v in os.environ.items()
                    if k.startswith(("HOROVOD_", "HVD_TPU_"))}
            os.environ.clear()
            os.environ.update(base_env)
            return results

    class FakeSC:
        defaultParallelism = 2

        def parallelize(self, rng, n):
            return FakeRDD(n)

    fake_pyspark = types.ModuleType("pyspark")
    fake_pyspark.SparkContext = types.SimpleNamespace(
        _active_spark_context=FakeSC())

    class _BTC:
        _current = None

        @classmethod
        def get(cls):
            return cls._current

    fake_pyspark.BarrierTaskContext = _BTC
    monkeypatch.setitem(_sys.modules, "pyspark", fake_pyspark)

    from horovod_tpu import spark_integration
    out = spark_integration.run(
        lambda tag: f"{tag}-{os.environ['HOROVOD_RANK']}", args=("r",),
        num_proc=2)
    assert out == ["r-0", "r-1"]
    for i in range(2):
        env = captured_envs[i]
        assert env["HOROVOD_RANK"] == str(i)
        assert env["HOROVOD_SIZE"] == "2"
        assert env["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
        assert "HVD_TPU_COORDINATOR" in env


# ---------------------------------------------------------------------------
# NIC probing / interface intersection (driver_service.py:122-194 analog)
# ---------------------------------------------------------------------------

def test_probe_and_report_reachability():
    """The probe tests every candidate against the live KV port (the
    reachability test IS the registration transport) and publishes one
    report through a working candidate."""
    from horovod_tpu.runner.nic_probe import PROBE_SCOPE, probe_and_report
    kv = KVStoreServer()
    port = kv.start()
    try:
        ok = probe_and_report(
            "h1",
            [("127.0.0.1", port),   # live KV
             ("127.0.0.2", 1)],     # nothing listening
            interfaces={"eth0": ["10.0.0.9"]})
        assert ok
        rep = json.loads(kv.get(PROBE_SCOPE, "report/h1"))
        assert rep["interfaces"] == {"eth0": ["10.0.0.9"]}
        assert rep["reachable"] == ["127.0.0.1"]
    finally:
        kv.stop()


def test_probe_and_report_no_reachable_candidate():
    from horovod_tpu.runner.nic_probe import probe_and_report
    assert probe_and_report("h1", [("127.0.0.2", 1)],
                            interfaces={}) is False


def test_discover_common_address_end_to_end():
    """Launcher-side flow with in-process probes standing in for the
    ssh-launched remote ones (no sshd in this image; the ssh command
    synthesis is covered by test_remote_ssh_command_synthesis).
    Interface intersection includes the launcher's own interfaces, and
    the routable pick needs EVERY host to report the candidate."""
    from horovod_tpu.runner.nic_probe import (
        discover_common_address, local_interfaces, probe_and_report)
    kv = KVStoreServer()
    kv_port = kv.start()
    local_names = list(local_interfaces().keys())
    fake_ifaces = {
        "hA": {n: ["10.0.0.1"] for n in local_names + ["ibX"]},
        "hB": {n: ["10.0.0.2"] for n in local_names},
    }

    def spawn(host):
        probe_and_report(host, [("127.0.0.1", kv_port), ("127.0.0.2", 1)],
                         interfaces=fake_ifaces[host])

    try:
        common, routable = discover_common_address(
            kv, ["hA", "hB"], spawn,
            candidate_addrs=["127.0.0.2", "127.0.0.1"],
            candidate_port=kv_port, timeout=10)
        assert routable == "127.0.0.1"  # the only addr both hosts reached
        assert common == sorted(local_names)
    finally:
        kv.stop()


def test_discover_common_address_missing_probe_times_out():
    from horovod_tpu.runner.nic_probe import discover_common_address
    kv = KVStoreServer()
    kv.start()
    try:
        with pytest.raises(TimeoutError, match="never reported"):
            discover_common_address(kv, ["ghost"], lambda h: None,
                                    ["127.0.0.1"], 1, timeout=1.0)
    finally:
        kv.stop()


# ---------------------------------------------------------------------------
# LSF / jsrun launch path (reference runner/js_run.py:34 + util/lsf.py:35)
# ---------------------------------------------------------------------------

def test_lsf_host_discovery(monkeypatch, tmp_path):
    from horovod_tpu.runner import lsf
    monkeypatch.setenv("LSB_JOBID", "77")
    hf = tmp_path / "hostfile"
    hf.write_text("nodeA\nnodeA\nnodeB\n")
    monkeypatch.setenv("LSB_DJOB_HOSTFILE", str(hf))
    hosts = lsf.lsf_hosts()
    assert [(h.hostname, h.slots) for h in hosts] == \
        [("nodeA", 2), ("nodeB", 1)]
    # Fallback: LSB_MCPU_HOSTS pairs.
    monkeypatch.delenv("LSB_DJOB_HOSTFILE")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeA 4 nodeB 2")
    hosts = lsf.lsf_hosts()
    assert [(h.hostname, h.slots) for h in hosts] == \
        [("nodeA", 4), ("nodeB", 2)]
    monkeypatch.delenv("LSB_JOBID")
    with pytest.raises(RuntimeError, match="LSB_JOBID"):
        lsf.lsf_hosts()


_FAKE_JSRUN = """#!/bin/bash
# Minimal jsrun: read the ERF rankfile, start one local task per rank with
# the JSM namespace env, propagate the worst exit code (what jsrun does).
ERF=""
ARGS=()
while [ $# -gt 0 ]; do
  case "$1" in
    --erf_input) ERF="$2"; shift 2 ;;
    --stdio_stdout|--stdio_stderr) shift 2 ;;
    *) ARGS+=("$1"); shift ;;
  esac
done
N=$(grep -c '^rank:' "$ERF")
pids=()
for ((i=0; i<N; i++)); do
  JSM_NAMESPACE_RANK=$i JSM_NAMESPACE_SIZE=$N "${ARGS[@]}" &
  pids+=($!)
done
rc=0
for p in "${pids[@]}"; do wait "$p" || rc=1; done
exit $rc
"""


@pytest.mark.slow  # ~8s mock-jsrun e2e; static/ssh launch paths stay in tier-1
def test_jsrun_launch_end_to_end(monkeypatch, tmp_path):
    """--jsrun inside a (mocked) LSF allocation: hosts come from LSF env,
    ONE jsrun invocation covers both ranks, the shim maps JSM ranks onto
    the rendezvous slot records, and a REAL 2-rank collective runs."""
    import stat
    jsrun = tmp_path / "jsrun"
    jsrun.write_text(_FAKE_JSRUN)
    jsrun.chmod(jsrun.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    monkeypatch.setenv("LSB_JOBID", "4242")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "localhost 2")

    worker = tmp_path / "worker.py"
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    worker.write_text(textwrap.dedent(f"""
        import os, sys
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=1")
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {REPO!r})
        import jax.numpy as jnp
        import horovod_tpu as hvd
        hvd.init()
        v = hvd.allreduce(jnp.ones(2) * (hvd.rank() + 1), op=hvd.Sum)
        with open(os.path.join({str(out_dir)!r},
                               f"rank{{hvd.rank()}}.txt"), "w") as f:
            f.write(f"{{hvd.rank()}}/{{hvd.size()}}:{{float(v[0])}}")
    """))
    from horovod_tpu.runner import launch as launch_mod
    args = launch_mod.parse_args(
        ["--jsrun", sys.executable, str(worker)])
    assert launch_mod._run_static(args) == 0
    got = sorted((out_dir / f"rank{r}.txt").read_text() for r in (0, 1))
    assert got == ["0/2:3.0", "1/2:3.0"]


def test_jsrun_rejects_elastic_flags(monkeypatch, tmp_path, capsys):
    """--jsrun + elastic must error loudly: the elastic driver respawns
    workers over ssh and would silently ignore jsrun."""
    import stat
    jsrun = tmp_path / "jsrun"
    jsrun.write_text("#!/bin/bash\nexit 0\n")
    jsrun.chmod(jsrun.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    monkeypatch.setenv("LSB_JOBID", "1")
    with pytest.raises(SystemExit) as ei:
        parse_args(["--jsrun", "--min-np", "2", "--max-np", "4",
                    "python", "x.py"])
    assert ei.value.code == 2
    assert "cannot be combined with elastic flags" in capsys.readouterr().err
