"""Ray integration: discovery mapping and the elastic executor wiring,
tested against a FAKE cluster (reference pattern: test/single/test_ray*.py
run against a local ray; ray is absent from this image, so the node-state
API is stubbed and the actor-spawn layer is injected)."""

import sys
import threading
import time
import types

import pytest

from horovod_tpu.elastic.discovery import HostDiscovery


# ---------------------------------------------------------------------------
# RayHostDiscovery
# ---------------------------------------------------------------------------

def _fake_ray_module(nodes):
    mod = types.ModuleType("ray")
    mod.nodes = lambda: nodes
    return mod


def test_ray_host_discovery_cpu(monkeypatch):
    from horovod_tpu.ray_elastic import RayHostDiscovery
    monkeypatch.setitem(sys.modules, "ray", _fake_ray_module([
        {"Alive": True, "NodeManagerHostname": "n1",
         "Resources": {"CPU": 8.0}},
        {"Alive": True, "NodeManagerHostname": "n2",
         "Resources": {"CPU": 3.0}},
        {"Alive": False, "NodeManagerHostname": "dead",
         "Resources": {"CPU": 64.0}},
    ]))
    disc = RayHostDiscovery(cpus_per_worker=2)
    assert disc.find_available_hosts_and_slots() == {"n1": 4, "n2": 1}


def test_ray_host_discovery_gpu_and_tpu(monkeypatch):
    from horovod_tpu.ray_elastic import RayHostDiscovery
    nodes = [{"Alive": True, "NodeManagerHostname": "n1",
              "Resources": {"CPU": 16.0, "GPU": 4.0, "TPU": 8.0}}]
    monkeypatch.setitem(sys.modules, "ray", _fake_ray_module(nodes))
    assert RayHostDiscovery(use_gpu=True, gpus_per_worker=2) \
        .find_available_hosts_and_slots() == {"n1": 2}
    assert RayHostDiscovery(tpu_per_worker=4) \
        .find_available_hosts_and_slots() == {"n1": 2}
    # Zero-slot hosts are omitted entirely.
    assert RayHostDiscovery(use_gpu=True, gpus_per_worker=8) \
        .find_available_hosts_and_slots() == {}


# ---------------------------------------------------------------------------
# ElasticRayExecutor against a fake spawn layer
# ---------------------------------------------------------------------------

class MutableDiscovery(HostDiscovery):
    def __init__(self, hosts):
        self.hosts = dict(hosts)
        self.lock = threading.Lock()

    def find_available_hosts_and_slots(self):
        with self.lock:
            return dict(self.hosts)

    def set(self, hosts):
        with self.lock:
            self.hosts = dict(hosts)


class FakeHandle:
    """Stands in for a Ray actor: completes when the test fires ``finish``;
    reports the CURRENT driver world version (emulating the in-worker world
    refresh a survivor performs on reset)."""

    def __init__(self, entry, env, driver_getter, finish, killed):
        self.entry = entry
        self.env = env
        self.driver_getter = driver_getter
        self.finish = finish
        self.killed_list = killed
        self.killed = False

    def wait(self, timeout):
        if self.killed:
            return True
        return self.finish.wait(timeout)

    def result(self):
        if self.killed:
            return 143, None
        user_fn = self.entry.args[0]  # functools.partial(_worker_entry, fn..)
        ver = self.driver_getter().world_version
        return 0, (ver, int(self.env["HOROVOD_RANK"]),
                   int(self.env["HOROVOD_SIZE"]), user_fn())

    def kill(self):
        self.killed = True
        self.killed_list.append(int(self.env["HOROVOD_RANK"]))


def _make_executor(disc, min_w, max_w, finish, killed, spawned):
    from horovod_tpu.ray_elastic import ElasticRayExecutor
    holder = {}

    def spawn(entry, args, kwargs, env, slot):
        h = FakeHandle(entry, env, lambda: holder["ex"]._driver,
                       finish, killed)
        spawned.append(env)
        return h

    ex = ElasticRayExecutor(min_workers=min_w, max_workers=max_w,
                            override_discovery=disc, spawn_fn=spawn,
                            elastic_timeout=30)
    holder["ex"] = ex
    return ex


def test_elastic_ray_executor_static_world():
    disc = MutableDiscovery({"h1": 2})
    finish, killed, spawned = threading.Event(), [], []
    ex = _make_executor(disc, 2, 2, finish, killed, spawned)
    finish.set()  # workers complete immediately
    out = ex.run(lambda: "ok")
    ex.shutdown()
    assert out == ["ok", "ok"]
    ranks = sorted(int(e["HOROVOD_RANK"]) for e in spawned)
    assert ranks == [0, 1]
    for e in spawned:
        assert e["HOROVOD_ELASTIC"] == "1"
        assert e["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
        assert int(e["HOROVOD_GLOO_RENDEZVOUS_PORT"]) > 0
    assert killed == []


def test_elastic_ray_executor_scale_down_decommissions(monkeypatch):
    """Autoscaler shrink (h1: 3 -> 2): the slot-2 worker is killed and NOT
    recorded as a failure (no blacklist, run succeeds); survivors' results
    form the final world (elastic_v2 shrink semantics).  The fake worker
    has no graceful-exit path, so shorten the decommission grace window
    the driver gives real workers before the SIGTERM fallback."""
    from horovod_tpu.elastic import driver as driver_mod
    monkeypatch.setattr(driver_mod, "DECOMMISSION_GRACE_S", 0.3)
    disc = MutableDiscovery({"h1": 3})
    finish, killed, spawned = threading.Event(), [], []
    ex = _make_executor(disc, 2, 3, finish, killed, spawned)
    result_box = {}

    def run():
        result_box["out"] = ex.run(lambda: "ok")

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 15
    while len(spawned) < 3 and time.time() < deadline:
        time.sleep(0.05)
    assert len(spawned) == 3
    disc.set({"h1": 2})  # autoscaler removed a slot
    while not killed and time.time() < deadline:
        time.sleep(0.05)
    assert killed == [2], killed
    finish.set()  # survivors complete in the reshaped world
    t.join(timeout=30)
    assert not t.is_alive()
    ex.shutdown()
    assert result_box["out"] == ["ok", "ok"]
