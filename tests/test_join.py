"""Join semantics (JoinOp, collective_operations.h:308): uneven data across
real processes — joined ranks contribute zeros until everyone joins."""

import os
import subprocess
import sys

import pytest

import horovod_tpu as hvd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Serialize with the other subprocess-world e2e files (conftest
# pytest_collection_modifyitems): overlapping multi-process worlds on one
# host core cascade spurious stall timeouts.
pytestmark = pytest.mark.xdist_group("heavy_e2e")

WORKER_RANK1_JOINS_EARLY = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
if hvd.rank() == 1:
    last = hvd.join()     # no data: service peers with zeros
    print(f"RANK1 joined, last={{last}}")
else:
    # rank 0 keeps training for 3 steps after rank 1 ran out of data
    for step in range(3):
        out = hvd.allreduce(jnp.full((4,), 2.0), op=hvd.Sum, name="g")
        assert float(out[0]) == 2.0, f"step {{step}}: expected own value, got {{out}}"
    b = hvd.barrier  # noqa - just reference
    last = hvd.join()
    print(f"RANK0 trained 3 steps solo, last={{last}}")
assert last == 0  # rank 0 joined last
"""

WORKER_RANK0_JOINS_EARLY = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
if hvd.rank() == 0:
    last = hvd.join()     # the coordinator itself runs out of data first
    print(f"RANK0 joined, last={{last}}")
else:
    for step in range(2):
        out = hvd.allreduce(jnp.full((3,), 5.0), op=hvd.Sum, name="h")
        assert float(out[0]) == 5.0, f"step {{step}}: got {{out}}"
    last = hvd.join()
    print(f"RANK1 trained 2 steps solo, last={{last}}")
assert last == 1  # rank 1 joined last
"""


def _run(script_text, tmp_path, name):
    script = tmp_path / name
    script.write_text(script_text.format(repo=REPO))
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=240)


@pytest.mark.integration
def test_join_rank1_early(tmp_path):
    proc = _run(WORKER_RANK1_JOINS_EARLY, tmp_path, "j1.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "RANK1 joined, last=0" in proc.stdout
    assert "RANK0 trained 3 steps solo, last=0" in proc.stdout


@pytest.mark.integration
def test_join_coordinator_early(tmp_path):
    """Rank 0 (the negotiation coordinator) joins first: its service loop
    must keep coordinating the survivors' collectives via announcements."""
    proc = _run(WORKER_RANK0_JOINS_EARLY, tmp_path, "j0.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "RANK0 joined, last=1" in proc.stdout
    assert "RANK1 trained 2 steps solo, last=1" in proc.stdout


def test_join_emulated_trivial(hvd8):
    assert hvd8.join() == 7


WORKER_STAGGERED_3 = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
r = hvd.rank()
steps = {{0: 3, 1: 1, 2: 2}}[r]
for i in range(steps):
    out = hvd.allreduce(jnp.ones((2,)), op=hvd.Sum, name="g")
    alive = sum(1 for rr, s in {{0: 3, 1: 1, 2: 2}}.items() if s > i)
    assert abs(float(out[0]) - alive) < 1e-6, (i, float(out[0]), alive)
last = hvd.join()
print(f"rank{{r}}: staggered ok last={{last}}")
assert last == 0
"""


@pytest.mark.integration
def test_join_staggered_three_ranks(tmp_path):
    """Three ranks running out of data at different steps: each surviving
    round sums exactly the live ranks (regression for the stale-joinop
    replay deadlock)."""
    script = tmp_path / "j3.py"
    script.write_text(WORKER_STAGGERED_3.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "3",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(3):
        assert f"rank{r}: staggered ok last=0" in proc.stdout


WORKER_JOIN_ALLGATHER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import numpy as np
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
if hvd.rank() == 1:
    last = hvd.join()     # joined rank must service allgather-family replays
    print(f"RANK1 joined, last={{last}}")
else:
    # Ragged allgather while rank 1 is joined: the joined rank contributes
    # an EMPTY slice, so rank 0 gets exactly its own rows back.
    out = hvd.allgather(jnp.arange(6.0).reshape(3, 2), name="ag")
    assert out.shape == (3, 2), out.shape
    assert np.allclose(np.asarray(out), np.arange(6.0).reshape(3, 2))
    # allgather_object routes through the same ragged path.
    objs = hvd.allgather_object({{"r": 0}}, name="agobj")
    assert {{"r": 0}} in objs, objs
    # alltoall with splits (splits gather + padded gather) while joined.
    t = jnp.arange(4.0).reshape(4, 1)
    outp, rsplits = hvd.alltoall(t, splits=jnp.asarray([2, 2]), name="a2a")
    assert np.asarray(rsplits).tolist() == [2, 0], rsplits
    assert np.allclose(np.asarray(outp)[:2, 0], [0.0, 1.0]), outp
    last = hvd.join()
    print(f"RANK0 allgather-family under join ok, last={{last}}")
assert last == 0
"""


@pytest.mark.integration
def test_join_allgather_family(tmp_path):
    """Regression (ADVICE r1, medium): allgather/alltoallv/allgather_object
    issued while a peer is joined used to deadlock — the joinop replay
    re-entered the public ragged path and nested a size exchange no live
    rank ever issued.  Replays now mirror the raw inner dispatches."""
    proc = _run(WORKER_JOIN_ALLGATHER, tmp_path, "jag.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "RANK1 joined, last=0" in proc.stdout
    assert "RANK0 allgather-family under join ok, last=0" in proc.stdout


WORKER_JOIN_STRESS = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
r = hvd.rank()
ROUNDS = 40
for round_ in range(ROUNDS):
    # Deterministic per-rank step counts, rotating so every rank joins
    # first/last across rounds; every allreduce after the first round is a
    # cache HIT racing the peers' join markers.
    steps = [(round_ + i) % 3 + 1 for i in range(3)]
    for i in range(steps[r]):
        out = hvd.allreduce(jnp.ones((8,)), op=hvd.Sum, name="g")
        alive = sum(1 for rr in range(3) if steps[rr] > i)
        assert abs(float(out[0]) - alive) < 1e-6, (round_, i, float(out[0]), alive)
    hvd.join()
print(f"rank{{r}} STRESS OK after {{ROUNDS}} rounds")
"""


@pytest.mark.integration
@pytest.mark.slow  # ~9s stress loop
def test_join_cached_dispatch_stress(tmp_path):
    """VERDICT r1 item 2: interleave cache-HIT dispatches with joins across
    3 processes for 40 rounds (~160 collectives racing join markers).  The
    replayable dispatch stream must close the join-onset window: no
    deadlock, no timeout, exact live-rank sums every step."""
    script = tmp_path / "jstress.py"
    script.write_text(WORKER_JOIN_STRESS.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "3",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(3):
        assert f"rank{r} STRESS OK" in proc.stdout


# ---------------------------------------------------------------------------
# Join + non-global process sets (TODO.md parity gap): the wire identity of
# a process set is its MEMBERSHIP (ops._wire_ps), never the local
# registration-order id — so ranks may register sets in different orders,
# and a joined rank replays subset collectives against sets it never saw.
# ---------------------------------------------------------------------------

def test_wire_ps_is_order_independent(hvd8):
    from horovod_tpu.ops import _wire_ps
    from horovod_tpu.process_sets import ProcessSet, global_process_set
    a = ProcessSet([0, 2, 5])
    b = ProcessSet([5, 0, 2])     # same membership, different spelling
    a.process_set_id, b.process_set_id = 7, 93   # wildly different local ids
    wa, wb = _wire_ps(a), _wire_ps(b)
    assert wa == wb
    assert wa["ps_ranks"] == [0, 2, 5]
    assert wa["ps_id"] not in (0, 7, 93)
    assert _wire_ps(global_process_set) == {"ps_id": 0, "ps_ranks": None}
    c = ProcessSet([0, 2, 6])
    c.process_set_id = 7
    assert _wire_ps(c)["ps_id"] != wa["ps_id"]  # membership-sensitive


WORKER_PS_ORDER_MISMATCH = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
r = hvd.rank()
# The SAME two sets registered in OPPOSITE orders: local ids differ across
# ranks ({{0,1}} is id 1 on rank 0 but id 2 on ranks 1/2, etc.).  The wire
# identity is membership, so collectives over either set must validate.
if r == 0:
    ps01 = hvd.add_process_set([0, 1]); ps12 = hvd.add_process_set([1, 2])
else:
    ps12 = hvd.add_process_set([1, 2]); ps01 = hvd.add_process_set([0, 1])
for step in range(3):
    out = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                        name="sub01", process_set=ps01)
    if r in (0, 1):
        assert abs(float(out[0]) - 3.0) < 1e-6, (r, float(out[0]))
    else:
        assert abs(float(out[0]) - 3.0) < 1e-6 or True  # non-member keeps own
    out = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                        name="sub12", process_set=ps12)
    if r in (1, 2):
        assert abs(float(out[0]) - 5.0) < 1e-6, (r, float(out[0]))
print(f"rank{{r}} PSORDER OK")
"""


def test_process_set_registration_order_mismatch(tmp_path):
    """Ranks registering identical sets in different orders used to produce
    cross-rank ps_id mismatches (validation error at best).  With the
    membership-canonical wire id, order does not matter."""
    script = tmp_path / "psorder.py"
    script.write_text(WORKER_PS_ORDER_MISMATCH.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "3",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(3):
        assert f"rank{r} PSORDER OK" in proc.stdout


WORKER_JOIN_SUBSET_PS = """
import jax
jax.config.update('jax_platforms','cpu')
import sys; sys.path.insert(0, {repo!r})
import horovod_tpu as hvd, jax.numpy as jnp
hvd.init()
r = hvd.rank()
if r == 2:
    # Rank 2 never registers the subset — it joins immediately and must
    # auto-register {{0,1}} from the replayed record's wire membership.
    last = hvd.join()
    print(f"rank2 joined, last={{last}}")
else:
    ps01 = hvd.add_process_set([0, 1])
    for step in range(3):
        out = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                            name="sub", process_set=ps01)
        # Members reduce over {{0,1}}: 1+2=3 (rank 2's replayed zeros are
        # masked out of the subset anyway).
        assert abs(float(out[0]) - 3.0) < 1e-6, (r, step, float(out[0]))
    last = hvd.join()
    print(f"rank{{r}} subset-under-join ok, last={{last}}")
"""


def test_join_with_unregistered_subset_process_set(tmp_path):
    """A joined rank servicing a subset collective it never registered must
    resolve the set from the record's membership, not a local id (which
    does not exist on that rank)."""
    script = tmp_path / "jps.py"
    script.write_text(WORKER_JOIN_SUBSET_PS.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "3",
         sys.executable, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rank2 joined" in proc.stdout
    assert "rank0 subset-under-join ok" in proc.stdout
    assert "rank1 subset-under-join ok" in proc.stdout
