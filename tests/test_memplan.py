"""hvdmem — static HBM liveness, donation, and budget analysis (HVD3xx).

Acceptance coverage (ISSUE 10):

* liveness-walk unit tests with HAND-COMPUTED peak bytes for
  straight-line / scan (carry-aware, not multiplied by trip count) /
  cond (branches max'd) / pjit (wrapper unwrapped, donation honored)
  jaxprs;
* a seeded corpus firing each of HVD300-HVD304 exactly where expected,
  with clean-fixture negatives (donated arg, scan-carry reuse, small
  intentional f32 islands, under-threshold fusion buckets);
* HVD301 statically flags a regression-test reproduction of the PR 4
  donated-then-consumed cache bug;
* HVD302 flags a BlockManager pool deliberately sized past a 1 GiB
  HVD_MEM_BUDGET_BYTES, and the headroom surfaces as
  ``kv_headroom_bytes`` on kv_stats/healthz/metrics;
* the liveness estimate for the serve decode program is within 2x of
  the summed cache+weights bytes the engine actually allocates (live
  array nbytes on the CPU backend);
* ROADMAP-5 lint gap: the serve prefill/decode programs get a
  collective census under HVD_ANALYZE=1 and census ZERO collectives;
* the ``--mem`` CLI honors the shared exit-code / pragma / prefix
  ``--select HVD3`` contract.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import core as _core
from horovod_tpu.analysis import hook, memplan, unsuppressed
from horovod_tpu.analysis.cli import main as cli_main

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

F32 = 4  # bytes


# ---------------------------------------------------------------------------
# Liveness walk: hand-computed peaks
# ---------------------------------------------------------------------------

def test_straight_line_peak_donated():
    """x(4KB) -> y=x*2 -> z=y+1, x donated: the peak is x+y at the mul
    (x dies there); the add runs at y+z = the same 8KB."""
    def f(x):
        return x * 2.0 + 1.0

    r = memplan.measure_step_fn(f, (jnp.ones(1024, jnp.float32),),
                                label="line", donate_argnums=(0,))
    assert r.input_bytes == 1024 * F32
    assert r.output_bytes == 1024 * F32
    assert r.peak_live_bytes == 2 * 1024 * F32
    assert r.by_primitive["mul"] == {"count": 1, "bytes": 4096}
    assert r.by_primitive["add"] == {"count": 1, "bytes": 4096}


def test_straight_line_peak_pinned_inputs():
    """Same program, donation unknown: the caller still holds x, so the
    add's live set is x+y+z = 12KB."""
    def f(x):
        return x * 2.0 + 1.0

    r = memplan.measure_step_fn(f, (jnp.ones(1024, jnp.float32),),
                                label="pinned")
    assert r.peak_live_bytes == 3 * 1024 * F32


def test_scan_body_counted_once_not_times_trip_count():
    """A scan body's working set exists once per iteration SEQUENTIALLY:
    peak must be carry-aware (x + out + body transient), identical for
    length 3 and length 300 — never multiplied by trip count."""
    def make(length):
        def f(x):
            def body(c, _):
                return c * 2.0 + 1.0, ()
            out, _ = jax.lax.scan(body, x, None, length=length)
            return out
        return f

    r3 = memplan.measure_step_fn(make(3), (jnp.ones(1024, jnp.float32),),
                                 label="scan3", donate_argnums=(0,))
    r300 = memplan.measure_step_fn(make(300),
                                   (jnp.ones(1024, jnp.float32),),
                                   label="scan300", donate_argnums=(0,))
    assert r3.peak_live_bytes == r300.peak_live_bytes
    # x(4K) + scan-out(4K) + body transient (c*2 lives next to c and the
    # add result beyond the 4K boundary: 4K) = 12K.
    assert r3.peak_live_bytes == 3 * 1024 * F32


def test_cond_branches_maxed_not_summed():
    """Branches are exclusive at runtime: a fat branch (two 4KB temps
    beyond the boundary) and a thin one (none) contribute max(8K, 0),
    not the sum."""
    def f(x):
        def fat(z):
            return (z * 2.0) + (z * 3.0)

        def thin(z):
            return z

        return jax.lax.cond(jnp.sum(x) > 0, fat, thin, x)

    r = memplan.measure_step_fn(f, (jnp.ones(1024, jnp.float32),),
                                label="cond", donate_argnums=(0,))
    # entry x=4K; cond eqn: out 4K + transient(fat) = max over branch
    # programs. fat: boundary 4K; z*2 -> 8K; z*3 -> 12K (z still live);
    # add -> 12K; transient = 12K - 4K = 8K.  Peak = 16K (+ the
    # predicate scalars) — and decisively NOT fat+thin summed (20K+).
    assert 4 * 1024 * F32 <= r.peak_live_bytes <= 4 * 1024 * F32 + 64
    assert r.peak_live_bytes < 5 * 1024 * F32


def test_pjit_wrapper_unwrapped_and_donation_read_from_it():
    """make_jaxpr of a jitted fn yields one pjit eqn; the walker descends
    into it and reads donated_invars off the wrapper — the donated cache
    dies at its last use instead of pinning."""
    def f(cache, x):
        return cache.at[0].set(x.sum()), x * 2.0

    big = jnp.ones((2048,), jnp.float32)  # 8KB
    small = jnp.ones((256,), jnp.float32)  # 1KB
    donated = memplan.measure_step_fn(jax.jit(f, donate_argnums=(0,)),
                                      (big, small), label="dj")
    pinned = memplan.measure_step_fn(jax.jit(f), (big, small), label="pj")
    assert donated.peak_live_bytes < pinned.peak_live_bytes
    # Both walked the INNER program, not just one opaque pjit eqn.
    assert "scatter" in donated.by_primitive


def test_closure_captured_consts_stay_pinned():
    """Closure-captured weights land in the jaxpr's constvars under
    make_jaxpr; the caller (ClosedJaxpr.consts) holds them for the whole
    call, so the walk must pin them like non-donated invars — not free
    them after their last read (which masked HVD302 on closed-over
    params)."""
    w = jnp.ones(1024, jnp.float32)  # 4KB, used ONLY in the first eqn

    def f(x):
        y = x + w
        big = jnp.concatenate([y, y, y, y])  # 16KB
        return big * 2.0                     # 16KB

    r = memplan.measure_step_fn(f, (jnp.ones(1024, jnp.float32),),
                                label="const-pin", donate_argnums=(0,))
    # Entry w+x=8K; add: +y=12K, x dies -> 8K; concat: +16K=24K, y dies
    # -> 20K; mul: +16K = 36K peak WITH w still resident.  An unpinned
    # walk frees w after the add and lands at 32K.
    assert r.peak_live_bytes == 9 * 1024 * F32


def test_sharding_divisor_reads_spec_axes():
    """pjit sharded dims divide by the product of the named mesh axis
    sizes (duck-typed: any .spec/.mesh.shape sharding works)."""
    class _Mesh:
        shape = {"dp": 8, "tp": 4}

    class _Sharding:
        spec = ("dp", None)
        mesh = _Mesh()

    class _Both:
        spec = (("dp", "tp"), None)
        mesh = _Mesh()

    assert memplan.sharding_divisor(_Sharding()) == 8
    assert memplan.sharding_divisor(_Both()) == 32
    assert memplan.sharding_divisor(object()) == 1


def test_shard_map_accounts_per_shard_bytes(hvd8):
    """A shard_map wrapper's body avals are per-shard: the walk of a
    jit(shard_map(f)) program sees bytes already divided by the mesh
    axis size for the sharded dim."""
    from jax.sharding import PartitionSpec as P
    mesh = hvd8.mesh()

    def local(x):
        return x * 2.0

    stepped = jax.jit(jax.shard_map(local, mesh=mesh, in_specs=P("hvd"),
                                    out_specs=P("hvd")))
    n = hvd8.num_slots()
    r = memplan.measure_step_fn(stepped, (jnp.ones((n * 1024,),
                                                   jnp.float32),),
                                label="sharded")
    # Per-shard: 1024 f32 in + 1024 f32 out (input pinned: donation
    # unknown) = 8KB, NOT the global 8KB * n.
    assert r.input_bytes == 1024 * F32
    assert r.peak_live_bytes == 2 * 1024 * F32


# ---------------------------------------------------------------------------
# Jaxpr rules: HVD300 / HVD302 / HVD303 / HVD304 + negatives
# ---------------------------------------------------------------------------

def test_hvd300_fires_on_undonated_matching_arg_and_not_when_donated():
    def f(cache, t):
        return cache.at[0].set(t.sum()), t * 1.0

    big = jnp.ones((1 << 19,), jnp.float32)  # 2 MiB: above the floor
    r = memplan.measure_step_fn(jax.jit(f), (big, jnp.ones(4)),
                                label="undonated")
    assert [x.rule for x in r.findings] == ["HVD300"]
    assert "donate" in r.findings[0].message
    r_ok = memplan.measure_step_fn(jax.jit(f, donate_argnums=(0,)),
                                   (big, jnp.ones(4)), label="donated")
    assert r_ok.ok(), [x.message for x in r_ok.findings]


def test_hvd300_ignores_small_args():
    """Donating a [B]-sized token vector saves nothing — below the
    byte floor no finding fires (the serve decode programs' token rows
    stay clean)."""
    def f(tok):
        return tok + 1

    r = memplan.measure_step_fn(jax.jit(f), (jnp.ones(8, jnp.int32),),
                                label="small")
    assert r.ok()


def test_hvd300_donated_arg_consumes_its_aliased_output():
    """fn(new, old) donating arg 0 with ONE output of that shape+dtype:
    XLA aliases the output to the donated buffer, so the output is
    spoken for — arg 1 must NOT be flagged (donating it buys nothing)."""
    def f(new, old):
        return new + old

    big = jnp.ones((1 << 19,), jnp.float32)  # 2 MiB each
    r = memplan.measure_step_fn(jax.jit(f, donate_argnums=(0,)),
                                (big, big + 1), label="aliased")
    assert r.ok(), [x.message for x in r.findings]


def test_hvd300_one_output_flags_at_most_one_of_two_matching_args():
    """f(a, b) -> one matching output: at most ONE donation is usable,
    so exactly one HVD300 fires — matches are consumed, not re-counted
    per arg."""
    def f(a, b):
        return a + b

    big = jnp.ones((1 << 19,), jnp.float32)
    r = memplan.measure_step_fn(jax.jit(f), (big, big + 1), label="pair")
    assert [x.rule for x in r.findings] == ["HVD300"]


def test_hvd302_peak_exceeds_budget():
    def f(x):
        return x * 2.0 + 1.0

    x = jnp.ones((1024,), jnp.float32)
    r = memplan.measure_step_fn(f, (x,), label="tight",
                                budget_bytes=8 * 1024)
    assert [x_.rule for x_ in r.findings] == ["HVD302"]
    assert r.headroom_bytes < 0
    ok = memplan.measure_step_fn(f, (x,), label="roomy",
                                 budget_bytes=1 << 20)
    assert ok.ok() and ok.headroom_bytes > 0


def test_hvd303_upcast_blowup_and_small_island_negative():
    def widen(p):
        return p.astype(jnp.float32) * 2.0

    p = jnp.ones((4096,), jnp.bfloat16)
    r = memplan.measure_step_fn(widen, (p,), label="widen",
                                upcast_min_bytes=1024)
    assert [x.rule for x in r.findings] == ["HVD303"]
    assert r.upcast_f32_bytes == 4096 * F32
    # The intentional f32 island under the documented knob (layernorm-
    # style, a few KB) stays below the default floor: clean.
    r_ok = memplan.measure_step_fn(widen, (p,), label="island")
    assert r_ok.ok()


def test_upcast_floor_knob_read_per_call_and_malformed_degrades(monkeypatch):
    """HVD_MEM_UPCAST_MIN_BYTES is read per call (not frozen at import)
    and a malformed value degrades to the 8 MiB default instead of
    raising — one typo'd env var must never brick the package import."""
    monkeypatch.setenv("HVD_MEM_UPCAST_MIN_BYTES", "8MB")
    assert memplan.upcast_min_bytes_default() == 8 << 20

    def widen(p):
        return p.astype(jnp.float32) * 2.0

    p = jnp.ones((4096,), jnp.bfloat16)
    monkeypatch.setenv("HVD_MEM_UPCAST_MIN_BYTES", "1024")
    r = memplan.measure_step_fn(widen, (p,), label="widen-env")
    assert [x.rule for x in r.findings] == ["HVD303"]


def test_hvd304_fusion_bucket_overshoot_and_under_threshold_negative():
    def fused(a, b):
        return jnp.concatenate([a.reshape(-1), b.reshape(-1)])

    a = jnp.ones((1024,), jnp.float32)
    b = jnp.ones((1024,), jnp.float32)
    r = memplan.measure_step_fn(fused, (a, b), label="bucket",
                                fusion_threshold=4 * 1024)
    assert [x.rule for x in r.findings] == ["HVD304"]
    assert "HOROVOD_FUSION_THRESHOLD" in r.findings[0].message
    r_ok = memplan.measure_step_fn(fused, (a, b), label="bucket-ok",
                                   fusion_threshold=64 * 1024)
    assert r_ok.ok()


# ---------------------------------------------------------------------------
# AST rules: HVD301 (the PR 4 hazard) / HVD300 source shapes
# ---------------------------------------------------------------------------

_PR4_REPRO = """
import jax

def decode_step(cache, tok):
    cache = cache.at[0].set(tok)
    return cache, tok + 1

def engine_loop(cache, tok):
    step = jax.jit(decode_step, donate_argnums=(0,))
    new_cache, nxt = step(cache, tok)
    stale = cache[0]
    return new_cache, nxt, stale
"""

_PR4_FIXED = _PR4_REPRO.replace(
    "    new_cache, nxt = step(cache, tok)\n    stale = cache[0]\n"
    "    return new_cache, nxt, stale",
    "    cache, nxt = step(cache, tok)\n    stale = cache[0]\n"
    "    return cache, nxt, stale")


def test_hvd301_flags_the_pr4_donated_then_consumed_bug():
    """Acceptance: the PR 4 cache hazard — cache donated into the jitted
    decode step, then read again — is flagged STATICALLY (instead of the
    runtime is_deleted check catching the deleted buffer mid-serve)."""
    findings = memplan.analyze_source(_PR4_REPRO, "pr4_repro.py")
    assert [f.rule for f in findings] == ["HVD301"]
    assert "donated" in findings[0].message
    assert findings[0].line == 11  # the stale read, not the call


def test_hvd301_rebinding_the_donated_name_is_clean():
    assert memplan.analyze_source(_PR4_FIXED, "pr4_fixed.py") == []


def test_hvd301_tracks_self_attribute_callables():
    src = """
import jax

class Engine:
    def setup(self, step):
        self._fn = jax.jit(step, donate_argnums=(1,))

    def run(self, params, cache, tok):
        out, nxt = self._fn(params, cache, tok)
        return out, nxt, cache["k"]
"""
    findings = memplan.analyze_source(src, "attr.py")
    assert [f.rule for f in findings] == ["HVD301"]


def test_hvd300_ast_jit_without_donation_of_updated_param():
    src = """
import jax

def build():
    def fn(params, cache, tok):
        ck = cache["k"]
        ck = ck.at[0].set(tok)
        return {"k": ck}, tok
    return jax.jit(fn)
"""
    findings = memplan.analyze_source(src, "h300.py")
    assert [f.rule for f in findings] == ["HVD300"]
    fixed = src.replace("jax.jit(fn)", "jax.jit(fn, donate_argnums=(1,))")
    assert memplan.analyze_source(fixed, "h300ok.py") == []


def test_hvd300_ast_scan_carry_reuse_is_exempt():
    """The scan-carry idiom: the body updates ITS OWN carry parameter —
    that is the clean functional-threading pattern, not a donation gap
    at the jit site (taint is scoped per function)."""
    src = """
import jax
import jax.numpy as jnp

def outer():
    def body(carry, x):
        carry = carry.at[0].set(x)
        return carry, x

    def fn(xs):
        c, ys = jax.lax.scan(body, jnp.zeros(4), xs)
        return ys
    return jax.jit(fn)
"""
    assert memplan.analyze_source(src, "scan.py") == []


def test_pragma_suppression_and_audit_trail():
    src = _PR4_REPRO.replace(
        "    stale = cache[0]",
        "    stale = cache[0]  # hvdlint: disable=HVD301")
    findings = memplan.analyze_source(src, "sup.py")
    assert [f.rule for f in findings] == ["HVD301"]
    assert findings[0].suppressed  # still reported: auditable
    assert unsuppressed(findings) == []


# ---------------------------------------------------------------------------
# CLI contract: --mem rides the shared pass registry
# ---------------------------------------------------------------------------

def test_mem_cli_exit_contract(tmp_path, capsys):
    """--mem honors the exact 0/1/2 contract lint and --race define: 0
    clean, 1 findings (incl. HVD000 parse failures and missing paths)."""
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(_PR4_REPRO)
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")

    for args, expected in (
            ([str(clean)], 0),
            ([str(dirty)], 1),
            ([str(bad)], 1),
            (["/nonexistent/mem/path"], 1)):
        rc = cli_main(["--mem"] + args)
        capsys.readouterr()
        assert rc == expected, (args, rc)
    # Parse-failure / missing-path classes agree across all three passes.
    for args in ([str(bad)], ["/nonexistent/mem/path"]):
        rcs = {cli_main(flag + args)
               for flag in ([], ["--race"], ["--mem"])}
        capsys.readouterr()
        assert rcs == {1}


def test_select_prefix_works_uniformly_across_passes(tmp_path, capsys):
    """--select HVD3 (a prefix) runs the whole HVD3xx family; the same
    prefix under the lint pass selects nothing — one filter, every
    pass."""
    dirty = tmp_path / "dirty.py"
    dirty.write_text(_PR4_REPRO)
    assert cli_main(["--mem", "--select", "HVD3", str(dirty)]) == 1
    capsys.readouterr()
    assert cli_main(["--mem", "--select", "HVD302", str(dirty)]) == 0
    capsys.readouterr()
    assert cli_main(["--select", "HVD3", str(dirty)]) == 0  # lint pass
    capsys.readouterr()


def test_mem_cli_json_format(tmp_path, capsys):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(_PR4_REPRO)
    rc = cli_main(["--mem", "--format", "json", str(dirty)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["pass"] == "mem"
    assert out["summary"]["by_rule"] == {"HVD301": 1}


def test_mem_dogfood_command_exits_zero(capsys):
    """The acceptance command: python -m horovod_tpu.analysis --mem
    horovod_tpu examples (in-process — same code path)."""
    rc = cli_main(["--mem", os.path.join(_REPO, "horovod_tpu"),
                   os.path.join(_REPO, "examples")])
    capsys.readouterr()
    assert rc == 0


# ---------------------------------------------------------------------------
# Serve integration: HVD_ANALYZE census + liveness vs real allocation,
# pool-budget HVD302, kv_headroom_bytes surfaces
# ---------------------------------------------------------------------------

@pytest.fixture()
def analyze_env(monkeypatch):
    monkeypatch.setenv("HVD_ANALYZE", "1")
    hook.reset()
    _core._state.analysis_reports = []
    yield
    hook.reset()


def _small_engine(**kw):
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.serve import (InferenceEngine, ServeMetrics,
                                   TransformerAdapter)
    cfg = TransformerConfig(vocab_size=64, causal=True,
                            dtype=jnp.float32, scan_layers=False,
                            num_layers=2, num_heads=2, d_model=32,
                            d_ff=64, max_len=32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    adapter = TransformerAdapter(cfg, params, block_tokens=8)
    engine = InferenceEngine(adapter, max_batch=2, kv_mode="paged",
                             metrics=ServeMetrics(),
                             replica_id="memplan-test", **kw)
    return adapter, engine


def test_serve_programs_census_zero_collectives(analyze_env):
    """ROADMAP-5 lint gap closed: the engine's prefill/decode builders
    register with the HVD_ANALYZE hook, so their first compile gets the
    HVD1xx walk + collective census — and a serving replica, being
    data-parallel and self-contained, must census ZERO collectives.
    This is the invariant that catches a future model-parallel serve
    program sneaking a collective into an unregistered path."""
    adapter, engine = _small_engine()
    out = engine.generate([1, 2, 3, 4, 5], max_new_tokens=4)
    engine.stop()
    assert len(out) == 4
    reports = _core.analysis_reports()
    serve_labels = [r.label for r in reports
                    if r.label.startswith("serve:")]
    assert any("prefill_chunk" in lb for lb in serve_labels)
    assert any("decode_paged" in lb for lb in serve_labels)
    for r in reports:
        if r.label.startswith("serve:"):
            assert r.census == {}, (r.label, r.census)
            assert not [f for f in r.findings if f.rule != "HVD303"], \
                [(f.rule, f.message) for f in r.findings]


def test_serve_decode_liveness_within_2x_of_real_allocation(analyze_env):
    """Acceptance: the liveness estimate for the serve decode program is
    within 2x of the summed cache+weights bytes the engine actually
    allocates (live array nbytes on the CPU backend).  The walk's only
    systematic over-count is the one transient pool copy at the scatter
    (XLA aliases it via donation), which is bounded by the pool size —
    hence < 2x by construction."""
    adapter, engine = _small_engine()
    engine.generate([1, 2, 3, 4, 5], max_new_tokens=4)
    engine.stop()
    reports = [r for r in _core.analysis_reports()
               if r.label.startswith("serve:decode_paged")]
    assert reports, [r.label for r in _core.analysis_reports()]
    peak = reports[0].memory["peak_live_bytes"]
    actual = (memplan.params_bytes(adapter.params)
              + memplan.params_bytes(engine._cache))
    assert actual > 0
    assert actual / 2 <= peak <= actual * 2, (peak, actual)


def test_hvd302_flags_pool_past_1gib_budget(monkeypatch):
    """Acceptance: a BlockManager pool deliberately sized past a 1 GiB
    HVD_MEM_BUDGET_BYTES fires HVD302 at engine construction (before
    anything OOMs), and the negative headroom is visible on
    kv_stats/healthz/metrics."""
    from horovod_tpu.serve import (InferenceEngine, MLPAdapter, Replica,
                                   ServeMetrics)
    from horovod_tpu.models import create_mlp

    monkeypatch.setenv("HVD_MEM_BUDGET_BYTES", str(1 << 30))  # 1 GiB
    _core._state.analysis_reports = []

    vocab = 16
    mlp = create_mlp(features=(8, vocab))
    params = mlp.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, vocab)))["params"]

    class _FatBlockAdapter(MLPAdapter):
        """Reports a 64 MiB per-block cost without allocating it — the
        budget check verifies the ACCOUNTING plan, not a real 2 GiB
        allocation on the test box."""
        max_blocks_per_seq = 4
        block_tokens = 16
        kv_token_cost = 0

        def paged_block_bytes(self):
            return 64 << 20

    adapter = _FatBlockAdapter(mlp, params, vocab_size=vocab)
    metrics = ServeMetrics()
    engine = InferenceEngine(adapter, max_batch=2, kv_mode="paged",
                             num_blocks=32,  # 32 x 64 MiB = 2 GiB
                             metrics=metrics, replica_id="fat-pool")
    # HVD302 published at construction.
    mem_reports = [r for r in _core.analysis_reports()
                   if getattr(r, "label", "").endswith("kv-pool")]
    assert mem_reports
    assert [f.rule for f in mem_reports[0].findings] == ["HVD302"]
    assert "exceeds the memory budget" in mem_reports[0].findings[0].message
    # Negative headroom on every surface: kv_stats, healthz, /metrics.
    stats = engine.kv_stats()
    assert stats["pool_bytes"] == 32 * (64 << 20)
    assert stats["kv_headroom_bytes"] < 0
    replica = Replica("fat-pool", None, engine)
    assert replica.to_dict()["kv_blocks"]["kv_headroom_bytes"] < 0
    metrics.register_kv_stats("fat-pool", engine.kv_stats)
    exposition = metrics.render()
    assert 'hvd_serve_kv_headroom_bytes{replica="fat-pool"}' in exposition


def test_pool_within_budget_has_positive_headroom(monkeypatch):
    monkeypatch.setenv("HVD_MEM_BUDGET_BYTES", str(1 << 30))
    _core._state.analysis_reports = []
    adapter, engine = _small_engine()
    stats = engine.kv_stats()
    assert stats["kv_headroom_bytes"] > 0
    assert not [r for r in _core.analysis_reports()
                if getattr(r, "label", "").endswith("kv-pool")]


def test_memory_census_lands_on_timeline(tmp_path):
    """The MEMORY_CENSUS counter events mirror the collective census:
    one totals counter + one per allocating primitive."""
    from horovod_tpu.timeline import Timeline

    def f(x):
        return x * 2.0 + 1.0

    r = memplan.measure_step_fn(f, (jnp.ones(1024, jnp.float32),),
                                label="mem_step", donate_argnums=(0,))
    path = str(tmp_path / "mem_timeline.json")
    tl = Timeline(path, rank=0)
    tl.memory_census("mem_step", r.to_dict())
    tl.close()
    with open(path) as fh:
        events = json.load(fh)
    names = [e.get("name", "") for e in events]
    assert "MEMORY_CENSUS/mem_step" in names
    assert "MEMORY_CENSUS/mem_step/mul" in names
    totals = next(e for e in events
                  if e.get("name") == "MEMORY_CENSUS/mem_step")
    assert totals["ph"] == "C"
    assert totals["args"]["peak_live_bytes"] == r.peak_live_bytes


def test_hook_attaches_memory_to_training_reports(analyze_env, hvd8):
    """The HVD_ANALYZE hook runs the liveness walk on the SAME trace as
    the collective census — a shard_step report carries both."""
    from jax.sharding import PartitionSpec as P
    import horovod_tpu as hvd

    def local_step(x):
        return jax.lax.psum(x * 2.0, "hvd")

    step = hvd.shard_step(local_step, in_specs=(P("hvd"),),
                          out_specs=P("hvd"))
    step(jnp.ones((8, 128), jnp.float32))
    reports = _core.analysis_reports()
    assert len(reports) == 1
    assert reports[0].census["psum"]["count"] == 1
    assert reports[0].memory["peak_live_bytes"] > 0
    assert reports[0].memory["by_primitive"]
