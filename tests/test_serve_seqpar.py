"""ISSUE 20: hvdseqserve — sequence-parallel long-prompt prefill.

Pins the tentpole's contracts layer by layer:

* parallel/ring.py — ``ragged_fold`` (traced-offset online-softmax fold,
  the shared math under both ring_attention's hops and the serving
  engine's SP extents) matches a dense reference at ragged offsets
  across every mask mode;
* engine — SP prefill is TOKEN-IDENTICAL to the proven single-rank
  chunked path at block-boundary prompt lengths (k*BT, k*BT±1) and at
  both KV storage dtypes (native f32 and int8 — the handoff ships scale
  rows bit-exactly through the tier transport);
* faultline — the kill-rank drill: a rank dying mid-SP-prefill aborts
  the job with ZERO block leaks on every rank, and the whole request
  resubmits and completes (single-rank — requeued requests are
  SP-ineligible, so the retry always makes progress);
* compile stability — steady-state SP traffic never recompiles (pow2
  extent buckets; decode programs untouched), and the warmup lattice
  (HVD_SERVE_WARMUP) makes first-long-prompt *and* revived-replica
  traffic land entirely on warm programs;
* plan — ``check_replica_plan`` attributes the ring's per-prefill wire
  bytes: plan_go flips under a tiny HVD_COMM_BUDGET_BYTES while the
  decode path stays zero-collective;
* admission — the batcher's advisory third resource: long prompts past
  the world's transient-block capacity are still admitted, marked
  ``sp_denied`` (they prefill single-rank);
* hvdtrace — per-extent SP spans + handoff land under the request's
  prefill stage, and the ring layer's RING_HOP schedule reaches the
  engine-wired timeline.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu import faultline as fl
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.obs import tracing as tr
from horovod_tpu.parallel import ring
from horovod_tpu.serve import (BlockManager, DynamicBatcher,
                               InferenceEngine, Request,
                               TransformerAdapter)
from horovod_tpu.serve.batcher import sp_extent_tokens
from horovod_tpu.serve.seqpar import SPConfig, SPWorld

BT = 8

_TINY = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_len=64, causal=True,
                          dtype=jnp.float32, scan_layers=False)


@pytest.fixture(scope="module")
def tiny_params():
    model = Transformer(_TINY)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


# Shared per-dtype adapters: the prefill/decode/SP compile caches live
# on the adapter, so every engine in this module reuses them (the bench
# discipline) instead of recompiling per test.
@pytest.fixture(scope="module")
def adapters(tiny_params):
    return {kvd: TransformerAdapter(_TINY, tiny_params, block_tokens=BT,
                                    kv_dtype=kvd)
            for kvd in ("native", "int8")}


def _prompt(n, seed=3):
    return np.random.RandomState(seed).randint(0, 61, (n,)).tolist()


def _run_one(adapter, prompt, *, sp_ranks=0, max_new=6, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("prefill_chunk", 5)  # deliberately unaligned with BT
    kw.setdefault("prefix_cache", False)
    if sp_ranks:
        kw.setdefault("sp_min_tokens", 16)
        kw["sp_ranks"] = sp_ranks
    eng = InferenceEngine(adapter, kv_mode="paged",
                          replica_id=f"sp-t{sp_ranks}", **kw).start()
    try:
        r = Request(list(prompt), max_new_tokens=max_new)
        eng.batcher.submit(r)
        out = r.result(timeout=120)
        return out, r, eng.kv_stats(), eng
    finally:
        eng.stop()


# -- ragged fold vs dense reference ------------------------------------------

@pytest.mark.parametrize("mask_mode", [0, 1, 2])
def test_ragged_fold_matches_dense_reference(mask_mode):
    """Folding a sequence in ragged extents at traced global offsets
    must equal one dense softmax over the concatenation — the identity
    both ring_attention and the SP prefill engine stand on."""
    rng = np.random.RandomState(0)
    B, H, D, scale = 1, 2, 8, 0.25
    Sq, q_start = 5, 7
    q = jnp.asarray(rng.randn(B, Sq, H, D), jnp.float32)
    # Three extents with ragged true lengths inside pow2 buckets.
    extents = [(0, 8, 7), (8, 8, 5), (13, 4, 3)]  # (k_start, bucket, len)
    ks, vs = {}, {}
    for st, bucket, ln in extents:
        ks[st] = jnp.asarray(rng.randn(B, bucket, H, D), jnp.float32)
        vs[st] = jnp.asarray(rng.randn(B, bucket, H, D), jnp.float32)
    acc, m, l = ring.ragged_fold_init(q)
    for st, bucket, ln in extents:
        acc, m, l = ring.ragged_fold(
            q, ks[st], vs[st], q_start=jnp.int32(q_start),
            k_start=jnp.int32(st), k_len=jnp.int32(ln),
            acc=acc, m=m, l=l, scale=scale, mask_mode=mask_mode)
    got = np.asarray(ring.ragged_fold_finish(acc, m, l))

    k_all = np.concatenate([np.asarray(ks[st][:, :ln])
                            for st, _, ln in extents], axis=1)
    v_all = np.concatenate([np.asarray(vs[st][:, :ln])
                            for st, _, ln in extents], axis=1)
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), k_all) * scale
    iq = q_start + np.arange(Sq)[:, None]
    # GLOBAL key positions — extent (13, 4, 3) starts past extent
    # (8, 8, 5)'s true end, so column index != position.
    ik = np.concatenate([st + np.arange(ln)
                         for st, _, ln in extents])[None, :]
    if mask_mode == 1:
        s = np.where(iq >= ik, s, -np.inf)
    elif mask_mode == 2:
        s = np.where(iq > ik, s, -np.inf)
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", p, v_all)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_sp_extent_tokens_geometry():
    assert sp_extent_tokens(40, 4, 8) == 16   # ceil(40/4)=10 → block up
    assert sp_extent_tokens(64, 4, 8) == 16
    assert sp_extent_tokens(33, 4, 16) == 16  # trailing extents empty
    with pytest.raises(ValueError):
        sp_extent_tokens(8, 0, 8)


def test_sp_config_env(monkeypatch):
    monkeypatch.setenv("HVD_SERVE_SP", "4")
    monkeypatch.setenv("HVD_SERVE_SP_MIN_TOKENS", "99")
    cfg = SPConfig()
    assert cfg.enabled and cfg.ranks == 4 and cfg.min_tokens == 99
    monkeypatch.setenv("HVD_SERVE_SP", "0")
    assert not SPConfig().enabled
    with pytest.raises(ValueError):
        SPWorld(object(), 1, 16)


# -- engine bit-exactness -----------------------------------------------------

@pytest.mark.parametrize("plen", [3 * BT, 3 * BT - 1, 3 * BT + 1])
def test_sp_matches_single_rank_at_block_boundaries(adapters, plen):
    prompt = _prompt(plen)
    base, _, _, _ = _run_one(adapters["native"], prompt)
    got, _, stats, _ = _run_one(adapters["native"], prompt, sp_ranks=4)
    assert got == base
    assert stats["sp"]["jobs"] == 1 and stats["sp"]["aborts"] == 0
    assert stats["sp"]["sp_tokens"] == plen


def test_sp_matches_single_rank_int8(adapters):
    """int8 KV blocks: the extent handoff ships quantized payloads WITH
    their scale rows through pack_payload/unpack_payload — decode over
    handed-off blocks equals decode over locally-prefilled ones."""
    prompt = _prompt(5 * BT - 3, seed=11)
    base, _, _, _ = _run_one(adapters["int8"], prompt)
    got, _, stats, _ = _run_one(adapters["int8"], prompt, sp_ranks=4)
    assert got == base
    assert stats["sp"]["jobs"] == 1
    assert stats["sp"]["handoff_bytes"] > 0
    assert stats["sp"]["ring_hops"] == 3  # sum of causal folds, 4 ranks


# -- faultline: kill-rank mid-SP-prefill --------------------------------------

def test_kill_rank_mid_sp_prefill_resubmits_whole_no_leaks(adapters):
    prompt = _prompt(40, seed=7)
    base, _, _, _ = _run_one(adapters["native"], prompt)
    fl.install(fl.FaultPlan(
        [fl.FaultSpec("kill-rank", point="sp.prefill", step=0)]))
    try:
        got, r, stats, eng = _run_one(adapters["native"], prompt,
                                      sp_ranks=4)
    finally:
        fl.uninstall()
    assert got == base                 # faults cost latency, not answers
    assert r.requeues == 1             # resubmitted whole...
    assert stats["sp"]["jobs"] == 1
    assert stats["sp"]["aborts"] == 1  # ...after the world aborted
    # Zero leaks on EVERY rank: each side manager is fully free again.
    for m in eng.seqpar.managers:
        assert m.available() == eng.seqpar.blocks_per_rank
        assert m.stats()["used"] == 0
    # ... and the retry went single-rank (requeued → SP-ineligible), so
    # no second job was ever claimed.
    assert eng.metrics.snapshot()["sp"]["prefills"] == 0


# -- compile stability --------------------------------------------------------

def test_sp_steady_state_never_recompiles(adapters, tiny_params):
    """Second same-bucket long prompt: zero new SP chunk programs, and
    the decode program set is untouched by SP entirely."""
    ad = TransformerAdapter(_TINY, tiny_params, block_tokens=BT)
    prompt = _prompt(40, seed=5)
    eng = InferenceEngine(ad, kv_mode="paged", replica_id="sp-steady",
                          max_batch=8, prefill_chunk=5,
                          prefix_cache=False, sp_ranks=4,
                          sp_min_tokens=16).start()
    try:
        r1 = Request(list(prompt), max_new_tokens=4)
        eng.batcher.submit(r1)
        r1.result(timeout=120)
        sp_keys = set(ad._sp_chunk_cache)
        decode_keys = set(ad._paged_decode_fns)
        assert sp_keys  # the SP path really compiled something
        r2 = Request(list(_prompt(40, seed=6)), max_new_tokens=4)
        eng.batcher.submit(r2)
        r2.result(timeout=120)
        assert set(ad._sp_chunk_cache) == sp_keys        # the pin
        assert set(ad._paged_decode_fns) == decode_keys  # decode intact
        assert eng.kv_stats()["sp"]["jobs"] == 2
    finally:
        eng.stop()


def test_sp_warmup_lattice_and_revival(adapters, tiny_params):
    """HVD_SERVE_WARMUP covers the SP bucket lattice: real long-prompt
    traffic after warmup adds ZERO programs, and a revived engine
    (stop → start, the mark_alive path — PR 13 pin) re-runs warmup with
    the lattice already cached."""
    ad = TransformerAdapter(_TINY, tiny_params, block_tokens=BT)
    eng = InferenceEngine(ad, kv_mode="paged", replica_id="sp-warm",
                          max_batch=8, prefill_chunk=5,
                          prefix_cache=False, sp_ranks=4,
                          sp_min_tokens=16, warmup=True).start()
    try:
        assert eng.warmup_runs == 1
        warm_keys = set(ad._sp_chunk_cache)
        assert warm_keys  # the lattice compiled SP programs
        r = Request(list(_prompt(40, seed=9)), max_new_tokens=4)
        eng.batcher.submit(r)
        r.result(timeout=120)
        assert eng.kv_stats()["sp"]["jobs"] == 1
        assert set(ad._sp_chunk_cache) == warm_keys  # zero new compiles
        eng.stop()
        eng.start()                    # revival re-runs warmup (PR 13)
        assert eng.warmup_runs == 2
        assert set(ad._sp_chunk_cache) == warm_keys
    finally:
        eng.stop()


# -- plan census --------------------------------------------------------------

def test_sp_plan_attributes_ring_bytes(adapters, monkeypatch):
    eng = InferenceEngine(adapters["native"], kv_mode="paged",
                          replica_id="sp-plan", max_batch=8,
                          prefill_chunk=5, prefix_cache=False,
                          sp_ranks=4, sp_min_tokens=16)
    stats = eng.kv_stats()
    assert stats["sp"]["ring_bytes_per_prefill"] > 0
    assert eng.sp_comm_bytes == stats["sp"]["ring_bytes_per_prefill"]
    assert stats["plan_go"] is True
    # A single-rank engine attributes zero SP wire bytes (the decode
    # plane stays zero-collective — the ROADMAP-5 serving invariant).
    single = InferenceEngine(adapters["native"], kv_mode="paged",
                             replica_id="sp-plan0", max_batch=8,
                             prefill_chunk=5, prefix_cache=False)
    assert single.sp_comm_bytes == 0
    assert "sp" not in single.kv_stats()
    # A comm budget smaller than one prefill's rotation: no-go, surfaced
    # on healthz via kv_stats (plan_go — the hvdshard HVD401 check).
    monkeypatch.setenv("HVD_COMM_BUDGET_BYTES", "1")
    tight = InferenceEngine(adapters["native"], kv_mode="paged",
                            replica_id="sp-tight", max_batch=8,
                            prefill_chunk=5, prefix_cache=False,
                            sp_ranks=4, sp_min_tokens=16)
    assert tight.kv_stats()["plan_go"] is False


# -- admission ----------------------------------------------------------------

def test_sp_denied_is_advisory_not_rejection():
    """The third admission resource (transient extent blocks) never
    rejects: an over-capacity long prompt is admitted with sp_denied
    set, and short prompts are never charged."""
    b = DynamicBatcher(max_wait_ms=0.0)
    long1 = Request(list(range(40)), max_new_tokens=2)
    long2 = Request(list(range(40, 80)), max_new_tokens=2)
    short = Request([1, 2, 3], max_new_tokens=2)
    for r in (long1, long2, short):
        b.submit(r)
    got = b.get_admission(8, sp_min_tokens=16, sp_capacity=2,
                          sp_cost=lambda r: 2)
    assert got == [long1, long2, short]       # all admitted
    assert long1.sp_denied is False           # fit the capacity...
    assert long2.sp_denied is True            # ...which long1 drained
    assert short.sp_denied is False           # never charged


def test_sp_world_single_job_capacity(adapters):
    world = SPWorld(adapters["native"], 4, 16)
    assert world.free_extent_blocks() == world.blocks_per_rank
    assert world.extent_cost_blocks(40) == 2  # 16-token extent, BT=8
    assert world.ring_bytes_per_prefill() == 4 * 3 * world._hop_bytes()


# -- hvdtrace -----------------------------------------------------------------

class _HopTimeline:
    def __init__(self):
        self.hops = []

    def ring_hop(self, name, hop, **kw):
        self.hops.append((name, hop, kw))

    def trace_span(self, *a, **k):
        pass


def test_sp_spans_and_ring_hops_reach_the_tracer(adapters):
    """A traced request's SP prefill emits per-extent chunk + handoff
    spans under the request's trace, and the engine wires the ring
    layer's RING_HOP schedule at the tracer's timeline."""
    tracer = tr.install(tr.Tracer(sample=1.0))
    tl = _HopTimeline()
    tracer.set_timeline(tl)
    # 56 tokens / 4 ranks → 16-token block-rounded extents 16/16/16/8:
    # every rank owns a LIVE extent (40 would leave rank 3 empty).
    prompt = _prompt(56, seed=13)
    eng = InferenceEngine(adapters["native"], kv_mode="paged",
                          replica_id="sp-trace", max_batch=8,
                          prefill_chunk=5, prefix_cache=False,
                          sp_ranks=4, sp_min_tokens=16).start()
    try:
        r = Request(list(prompt), max_new_tokens=4)
        r.trace = tracer.new_context()
        eng.batcher.submit(r)
        r.result(timeout=120)
        assert eng.kv_stats()["sp"]["jobs"] == 1
        traces = tracer.recent_traces()
        spans = [s for t in traces if t["trace_id"] == r.trace.trace_id
                 for s in t["tree"]]
        names = [s["name"] for s in spans]
        assert "sp-extent-chunk" in names
        assert "sp-handoff" in names
        chunk_args = [s["args"] for s in spans
                      if s["name"] == "sp-extent-chunk"]
        assert {a["rank"] for a in chunk_args} == {0, 1, 2, 3}
        hand_args = [s["args"] for s in spans if s["name"] == "sp-handoff"]
        assert sum(a["bytes"] for a in hand_args) == \
            eng.kv_stats()["sp"]["handoff_bytes"]
        assert any(a["bytes"] == 0 for a in hand_args)  # rank-0 is local
        # RING_HOP schedule: n hops under the serve-qualified tensor
        # name, with the causal skip accounting.
        sp_hops = [h for h in tl.hops if "sp_prefill" in h[0]]
        assert len(sp_hops) == 4
        assert sp_hops[0][0].startswith("serve:sp-trace:sp/")
        assert {h[1] for h in sp_hops} == {0, 1, 2, 3}
        assert all(h[2]["bytes_rotated"] > 0 for h in sp_hops)
    finally:
        eng.stop()
        tr.uninstall()


def test_sp_prefill_stage_partitions_exactly(adapters):
    """stage_ms must still partition the request's wall: SP prefill
    accounts into the prefill stage (no new stage label)."""
    _, r, _, _ = _run_one(adapters["native"], _prompt(40, seed=17),
                          sp_ranks=4)
    assert set(r.stage_ms) >= {"queue", "prefill", "decode"}
    assert r.stage_ms["prefill"] > 0.0
    total = sum(r.stage_ms.values())
    assert total > 0.0
