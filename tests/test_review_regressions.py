"""Regression tests for review findings: subset scale restore, timeline
module, duplicate-name detection, ragged allgatherv, homogeneity."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.topology import Topology
from tests.test_collective_ops import run_spmd

N = 8


def test_subset_allreduce_nonmembers_get_unscaled_input(hvd8):
    x = jnp.asarray(np.arange(N, dtype=np.float32).reshape(N, 1))
    out = run_spmd(
        hvd8,
        lambda t: C.allreduce(t, C.Sum, members=(0, 1), prescale_factor=0.5),
        x)
    arr = np.asarray(x)
    np.testing.assert_allclose(np.asarray(out[0]), 0.5 * (arr[0] + arr[1]))
    # Non-members must see their ORIGINAL value, not a prescaled one.
    np.testing.assert_allclose(np.asarray(out[5]), arr[5])


def test_timeline_writes_valid_chrome_trace(tmp_path, hvd8):
    path = str(tmp_path / "timeline.json")
    hvd8.start_timeline(path, mark_cycles=True)
    x = jnp.ones((N, 4), jnp.float32)
    hvd8.allreduce(x, name="allreduce.grad0")
    hvd8.stop_timeline()
    events = json.load(open(path))
    names = {e["name"] for e in events}
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "ALLREDUCE" in names
    assert "XLA_EXECUTE" in names
    tids = {e.get("tid") for e in events}
    assert "allreduce.grad0" in tids


def test_timeline_env_knob_autostarts(tmp_path):
    path = str(tmp_path / "auto_timeline.json")
    os.environ["HOROVOD_TIMELINE"] = path
    try:
        hvd.shutdown()
        hvd.init()
        hvd.allreduce(jnp.ones((N, 2)), name="t")
        hvd.shutdown()
    finally:
        del os.environ["HOROVOD_TIMELINE"]
    events = json.load(open(path))
    assert any(e["name"] == "ALLREDUCE" for e in events)


def test_duplicate_name_error(hvd8):
    from horovod_tpu.exceptions import DuplicateNameError
    eng = hvd8.ops._engine()
    eng.claim_name("dup")
    with pytest.raises(DuplicateNameError):
        hvd8.allreduce(jnp.ones((N, 2)), name="dup")
    eng.release_name("dup")
    hvd8.allreduce(jnp.ones((N, 2)), name="dup")  # released → fine again


def test_allgatherv_ragged_emulated(hvd8):
    rng = np.random.RandomState(0)
    tensors = [jnp.asarray(rng.randn(r + 1, 2).astype(np.float32))
               for r in range(N)]
    outs = hvd8.allgather(tensors)
    expected = np.concatenate([np.asarray(t) for t in tensors], axis=0)
    assert expected.shape[0] == sum(range(1, N + 1))
    for r in range(N):
        np.testing.assert_allclose(np.asarray(outs[r]), expected, rtol=1e-6)


def test_allgatherv_ragged_subset(hvd8):
    rng = np.random.RandomState(1)
    tensors = [jnp.asarray(rng.randn(r + 1, 2).astype(np.float32))
               for r in range(N)]
    ps = hvd.add_process_set([1, 3])
    outs = hvd8.allgather(tensors, process_set=ps)
    expected = np.concatenate([np.asarray(tensors[1]), np.asarray(tensors[3])],
                              axis=0)
    np.testing.assert_allclose(np.asarray(outs[1]), expected, rtol=1e-6)
    # Non-member keeps own tensor.
    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(tensors[0]))
    hvd.remove_process_set(ps)


def test_is_homogeneous_heterogeneous_layout():
    t = Topology(rank=0, size=3, local_rank=0, local_size=1, cross_rank=0,
                 cross_size=3, num_slots=6, local_slots=1,
                 slots_per_node=[1, 2, 3])
    assert not t.is_homogeneous
    t2 = Topology(rank=0, size=3, local_rank=0, local_size=1, cross_rank=0,
                  cross_size=3, num_slots=6, local_slots=2,
                  slots_per_node=[2, 2, 2])
    assert t2.is_homogeneous


def test_broadcast_variables_param_with_leading_dim_n(hvd8):
    """A replicated weight whose first dim equals the emulated rank count
    must NOT be misread as a per-rank stack (review finding)."""
    w = jnp.asarray(np.random.RandomState(11).randn(N, 16).astype(np.float32))
    out = hvd.broadcast_variables({"w": w}, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(w))


def test_broadcast_stacked_flag_explicit(hvd8):
    x = jnp.asarray(np.random.RandomState(12).randn(N, 3).astype(np.float32))
    # explicit stacked=True keeps per-rank semantics
    out = hvd.broadcast(x, root_rank=2, stacked=True)
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r]), np.asarray(x)[2])
    # explicit stacked=False treats it as replicated
    out = hvd.broadcast(x, root_rank=2, stacked=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_duplicate_hostnames_merge():
    from horovod_tpu.runner import hosts as H
    slots = H.get_host_assignments(H.parse_hosts("h1:2,h1:2"), 4)
    pairs = [(s.hostname, s.local_rank) for s in slots]
    assert len(set(pairs)) == 4  # no duplicate (host, local_rank)
    assert all(s.cross_size == 1 for s in slots)


def test_spawn_failure_counts_as_rank_failure(tmp_path):
    import subprocess, sys, os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "1",
         "definitely_not_a_real_binary_xyz"],
        cwd=repo, capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0


def test_moe_unbound_expert_axis_raises_helpful_error():
    """ADVICE r2: init with expert_axis set outside shard_map must raise a
    ValueError naming the supported pattern, not an opaque NameError."""
    import jax
    from horovod_tpu.models.transformer import Transformer, TransformerConfig
    cfg = TransformerConfig(num_layers=2, num_heads=2, d_model=32, d_ff=64,
                            vocab_size=64, max_len=16, moe_experts=4,
                            expert_axis="ep")
    with pytest.raises(ValueError, match="expert_axis=None"):
        Transformer(cfg).init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 16), jnp.int32))


def test_resnet_checkpoint_migration_drops_stem_bias():
    """Pre-r3 checkpoints carried a redundant conv_init bias; the migration
    helper must drop it so the tree matches the current model."""
    import jax
    from horovod_tpu.models import create_resnet50
    from horovod_tpu.models.resnet import migrate_pre_r3_checkpoint
    model = create_resnet50(num_classes=10)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 64, 64, 3)), train=False)["params"]
    old = dict(params)
    old["conv_init"] = dict(old["conv_init"])
    old["conv_init"]["bias"] = jnp.zeros((64,))
    migrated = migrate_pre_r3_checkpoint(old)
    assert "bias" not in migrated["conv_init"]
    assert jax.tree_util.tree_structure(migrated) == \
        jax.tree_util.tree_structure(dict(params))


def test_rendezvous_liveness_broken_pipe_is_dead_signal():
    """ADVICE r2: BrokenPipeError (Python's mapping of EPIPE) must count as
    transport-dead; an HTTP-status OSError must not."""
    from horovod_tpu.elastic import _RendezvousLiveness
    lv = _RendezvousLiveness("h", 1)
    assert lv.note(BrokenPipeError(32, "broken pipe"))
    lv.ok()
    assert not lv.note(OSError("KV PUT failed: HTTP 500"))
