"""hvdlint AST linter: seeded violation corpus + clean fixtures + CLI.

Every HVD rule must fire exactly where the corpus plants it (rule, line)
and must NOT fire on the adjacent clean fixture — the acceptance bar for
the analyzer ("no false positives on the clean fixtures").  The CLI
contract (text/JSON output, exit codes, suppression pragmas, graceful
syntax-error handling) is exercised end to end in-process.
"""

import json
import textwrap

import pytest

from horovod_tpu.analysis import (Finding, RULES, lint_paths, lint_source,
                                  unsuppressed)
from horovod_tpu.analysis.cli import main as cli_main


def findings_of(src, **kw):
    return lint_source(textwrap.dedent(src), path="corpus.py", **kw)


def fired(src, **kw):
    return [(f.rule, f.line) for f in findings_of(src, **kw)
            if not f.suppressed]


# ---------------------------------------------------------------------------
# Violation corpus: one seeded violation per rule, asserted by (rule, line).
# ---------------------------------------------------------------------------

def test_hvd001_rank_guarded_collective():
    src = """\
    import horovod_tpu as hvd

    def main(p):
        if hvd.rank() == 0:
            p = hvd.broadcast_variables(p, root_rank=0)
        return p
    """
    assert fired(src) == [("HVD001", 5)]


def test_hvd001_bare_rank_variable_and_else_branch():
    src = """\
    import horovod_tpu as hvd

    def main(x, rank):
        if rank == 0:
            pass
        else:
            x = hvd.allreduce(x)
        return x
    """
    assert fired(src) == [("HVD001", 7)]


def test_hvd001_symmetric_branches_are_not_a_deadlock():
    """Identical collective sequences on both sides of a rank test mean
    every rank posts a matching collective (review regression)."""
    src = """\
    import horovod_tpu as hvd

    def main(x, buf):
        if hvd.rank() == 0:
            x = hvd.broadcast(x, root_rank=0)
        else:
            buf = hvd.broadcast(buf, root_rank=0)
        return x, buf
    """
    assert fired(src) == []
    # Asymmetric sequences still fire on both branches' collectives.
    asym = """\
    import horovod_tpu as hvd

    def main(x):
        if hvd.rank() == 0:
            x = hvd.allreduce(x)
        else:
            x = hvd.allgather(x)
        return x
    """
    assert fired(asym) == [("HVD001", 5), ("HVD001", 7)]


def test_hvd001_clean_rank_guarded_print_and_unguarded_collective():
    src = """\
    import horovod_tpu as hvd

    def main(x):
        x = hvd.allreduce(x)
        if hvd.rank() == 0:
            print("loss", x)
        return x
    """
    assert fired(src) == []


def test_hvd002_swallowed_collective():
    src = """\
    import horovod_tpu as hvd

    def main(x):
        try:
            x = hvd.allreduce(x)
        except Exception:
            x = None
        return x
    """
    assert fired(src) == [("HVD002", 5)]


def test_hvd002_clean_reraising_handler():
    src = """\
    import horovod_tpu as hvd

    def main(x):
        try:
            x = hvd.allreduce(x)
        except Exception:
            raise RuntimeError("rank failed") from None
        return x
    """
    assert fired(src) == []


def test_hvd003_unseeded_randomness_in_traced_fn():
    src = """\
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        return x * np.random.rand()
    """
    assert fired(src) == [("HVD003", 6)]


def test_hvd003_traced_via_call_argument_and_propagation():
    src = """\
    import jax
    import random

    def helper(x):
        return x + random.random()

    def step(x):
        return helper(x)

    step = jax.jit(step)
    """
    assert fired(src) == [("HVD003", 5)]


def test_hvd003_clean_seeded_and_untraced():
    src = """\
    import jax
    import numpy as np

    def host_data():
        return np.random.rand(8)          # not traced: fine

    @jax.jit
    def step(x, key):
        rng = np.random.RandomState(0)    # seeded: fine
        return x + jax.random.normal(key, x.shape)
    """
    assert fired(src) == []


def test_hvd004_print_in_traced_fn_and_clean_debug_print():
    src = """\
    import jax

    @jax.jit
    def step(x):
        print("tracing", x)
        jax.debug.print("x={x}", x=x)
        return x
    """
    assert fired(src) == [("HVD004", 5)]


def test_hvd005_block_until_ready_in_traced_fn():
    src = """\
    import jax

    @jax.jit
    def step(x):
        y = (x * 2).block_until_ready()
        return jax.device_get(y)
    """
    assert fired(src) == [("HVD005", 5), ("HVD005", 6)]


def test_hvd006_undeclared_axis_literal():
    src = """\
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh([], ("dp",))

    def f(x):
        return jax.lax.psum(x, "tp")
    """
    assert fired(src) == [("HVD006", 7)]


def test_hvd006_clean_declared_axis_and_no_declarations():
    clean = """\
    import jax
    from jax.sharding import Mesh

    mesh = Mesh([], ("dp",))

    def f(x):
        return jax.lax.psum(x, "dp")
    """
    assert fired(clean) == []
    # No declarations in the file -> nothing to check against.
    no_decl = """\
    import jax

    def f(x):
        return jax.lax.psum(x, "whatever")
    """
    assert fired(no_decl) == []


def test_hvd007_closed_over_mutation():
    src = """\
    import jax

    cache = {}

    @jax.jit
    def step(x):
        cache["x"] = x
        return x
    """
    assert fired(src) == [("HVD007", 7)]


def test_hvd007_factory_local_is_still_closed_over_for_the_trace():
    src = """\
    import jax

    def make_step():
        seen = []

        @jax.jit
        def step(x):
            seen.append(x)
            return x

        return step
    """
    assert fired(src) == [("HVD007", 8)]


def test_hvd007_clean_local_mutation_and_functional_update():
    src = """\
    import jax

    @jax.jit
    def step(x, buf):
        local = {}
        local["x"] = x              # local: fine
        buf = buf.at[0].add(x)      # functional update: fine
        return x, buf
    """
    assert fired(src) == []


def test_hvd008_wall_clock_in_traced_fn():
    src = """\
    import jax
    import time

    @jax.jit
    def step(x):
        return x + time.time()
    """
    assert fired(src) == [("HVD008", 6)]


def test_hvd008_clean_untraced_timing():
    src = """\
    import time

    def bench(step, x):
        t0 = time.perf_counter()
        step(x)
        return time.perf_counter() - t0
    """
    assert fired(src) == []


def test_hvd009_kv_transport_in_silent_except():
    src = """\
    def clear_marker(kv_client, host):
        try:
            kv_client.delete("preempt", host)
        except Exception:
            pass
    """
    assert fired(src) == [("HVD009", 3)]


def test_hvd009_bare_except_and_collective():
    """A bare `except:` counts whatever its body does, and the collective
    arm fires alongside HVD002 (same code, two severities of the same
    disease — HVD002's any-non-raising handler vs HVD009's silent
    shapes)."""
    src = """\
    import horovod_tpu as hvd

    def sync(x, log):
        try:
            x = hvd.allreduce(x)
        except:
            log.append("oops")
        return x
    """
    assert ("HVD009", 5) in fired(src)
    assert ("HVD002", 5) in fired(src)


def test_hvd009_clean_logged_handler_and_non_kv_calls():
    """A handler that LOGS (or otherwise acts) is not the silent shape;
    dict.get/plain attribute calls are not KV transport."""
    src = """\
    def heartbeat(kv_client, log, d):
        try:
            kv_client.put("tasks", "t0", b"hi")
        except Exception as e:
            log.warning("heartbeat failed: %s", e)
        try:
            d.get("key")
        except Exception:
            pass
    """
    assert fired(src) == []


def test_hvd009_ellipsis_body_is_silent():
    src = """\
    def gc(client):
        try:
            client.delete_scope("old")
        except Exception:
            ...
    """
    assert fired(src) == [("HVD009", 3)]


def _serve_fired(src):
    return [(f.rule, f.line) for f in lint_source(
        textwrap.dedent(src), path="horovod_tpu/serve/corpus.py")
        if not f.suppressed]


def test_hvd010_clock_seeded_serving_prng():
    src = """\
    import time
    import jax

    def handler():
        return jax.random.PRNGKey(int(time.time()))
    """
    assert _serve_fired(src) == [("HVD010", 5)]
    # datetime provenance counts as a clock too.
    src_dt = """\
    import datetime
    import jax

    def handler():
        seed = int(datetime.datetime.now().timestamp())
        return jax.random.fold_in(jax.random.PRNGKey(seed), 1)
    """
    # Only the PRNGKey(seed) site is clock-free (seed is a Name by the
    # time it reaches the call) — the clock lives in the assignment; the
    # WHOLE-expression form is what the rule sees through:
    src_inline = """\
    import datetime
    import jax

    def handler():
        return jax.random.PRNGKey(
            int(datetime.datetime.now().timestamp()))
    """
    assert _serve_fired(src_inline) == [("HVD010", 5)]
    del src_dt  # documented limitation: assigned-then-used clock seeds


def test_hvd010_constant_seeded_serving_prng():
    src = """\
    import jax

    def handler():
        k = jax.random.PRNGKey(0)
        return jax.random.fold_in(k, position)
    """
    assert _serve_fired(src) == [("HVD010", 4)]
    both_const = """\
    import jax

    def handler():
        return jax.random.fold_in(jax.random.PRNGKey(seed), 3)
    """
    assert _serve_fired(both_const) == []


def test_hvd010_request_derived_keys_are_clean_and_rule_is_serve_scoped():
    clean = """\
    import jax

    def seq_key(seed, sample_index):
        key = jax.random.fold_in(jax.random.PRNGKey(seed % (2 ** 31)),
                                 sample_index)
        return key

    def token_key(base_key, position):
        return jax.random.fold_in(base_key, int(position))
    """
    assert _serve_fired(clean) == []
    # The same constant seed OUTSIDE serve/ is fine (tests, examples,
    # training init all use PRNGKey(0) legitimately).
    dirty_elsewhere = """\
    import jax
    k = jax.random.PRNGKey(0)
    """
    assert fired(dirty_elsewhere) == []
    # dict.key()-shaped calls never match.
    not_prng = """\
    def f(d):
        return d.key(0)
    """
    assert [r for r, _ in _serve_fired(not_prng)] == []


def test_hvd011_sync_under_lock_three_shapes():
    """Each blocking-sync spelling fires under ``with self._lock``:
    .block_until_ready(), jax.device_get / bare device_get, and the
    host-numpy asarray that DMAs the value off the device."""
    src = """\
    import jax
    import numpy as np

    class Engine:
        def snapshot(self):
            with self._lock:
                out = self._logits.block_until_ready()
                host = jax.device_get(self._kv)
                arr = np.asarray(self._cache)
            return out, host, arr
    """
    assert _serve_fired(src) == [("HVD011", 7), ("HVD011", 8),
                                 ("HVD011", 9)]


def test_hvd011_snapshot_then_fetch_is_clean_and_serve_scoped():
    """The fix idiom — take the device reference under the lock,
    release, then sync — is clean; jnp.asarray stays on device; and the
    same dirty shape OUTSIDE serve/ (training checkpoint code blocks
    the only thread anyway) never fires."""
    clean = """\
    import jax
    import jax.numpy as jnp

    class Engine:
        def snapshot(self):
            with self._lock:
                ref = self._logits
                dev = jnp.asarray(self._cache)
            return jax.device_get(ref), dev
    """
    assert _serve_fired(clean) == []
    dirty_elsewhere = """\
    import jax

    def checkpoint(state, lock):
        with lock:
            return jax.device_get(state)
    """
    assert fired(dirty_elsewhere) == []


def test_hvd011_nested_defs_and_acquire_spelling():
    """A nested function defined (not called) under the lock runs later,
    possibly lock-free — skipped; ``with self._kv_lock.acquire()`` and a
    bare ``with lock:`` both count as lock regions."""
    src = """\
    import jax

    class Engine:
        def deferred(self):
            with self._lock:
                def fetch():
                    return jax.device_get(self._kv)
                self._pending = fetch
            return self._pending

        def direct(self, lock):
            with self._kv_lock.acquire():
                a = jax.device_get(self._kv)
            with lock:
                b = self._x.block_until_ready()
            return a, b
    """
    assert _serve_fired(src) == [("HVD011", 13), ("HVD011", 15)]


def test_join_collective_requires_hvd_base():
    """os.path.join / ','.join / thread.join must not read as the hvd.join
    collective (the false positives the first dogfooding run surfaced)."""
    src = """\
    import os
    import horovod_tpu as hvd

    def main(rank, t):
        if rank == 0:
            p = os.path.join("a", "b")
            s = ",".join(["x"])
            t.join()
        try:
            q = os.path.join("c", "d")
        except Exception:
            pass
        return p, s, q
    """
    assert fired(src) == []
    guarded = """\
    import horovod_tpu as hvd

    def main(rank):
        if rank == 0:
            hvd.join()
    """
    assert fired(guarded) == [("HVD001", 5)]


# ---------------------------------------------------------------------------
# Suppression, degradation, filters
# ---------------------------------------------------------------------------

def test_line_suppression_only_silences_named_rule():
    src = """\
    import jax
    import time

    @jax.jit
    def step(x):
        t = time.time()  # hvdlint: disable=HVD008
        print(t)  # hvdlint: disable=HVD004
        return x + time.perf_counter()
    """
    fs = findings_of(src)
    assert [(f.rule, f.line) for f in fs if f.suppressed] == \
        [("HVD008", 6), ("HVD004", 7)]
    assert [(f.rule, f.line) for f in fs if not f.suppressed] == \
        [("HVD008", 8)]


def test_pragma_in_string_literal_does_not_suppress():
    """Pragma-shaped text inside strings/docstrings must not silence the
    linter (review regression: line-regex scanning matched strings)."""
    src = '''\
    import jax
    import time

    DOC = "to silence a rule, write  # hvdlint: disable-file=all  ..."

    @jax.jit
    def step(x):
        """Help: use '# hvdlint: disable=HVD008' on the flagged line."""
        return x + time.time()
    '''
    assert fired(src) == [("HVD008", 9)]


def test_file_suppression_and_disable_all():
    src = """\
    # hvdlint: disable-file=HVD004
    import jax
    import time

    @jax.jit
    def step(x):
        print(x)
        t = time.time()  # hvdlint: disable=all
        return x
    """
    assert fired(src) == []
    assert len(findings_of(src)) == 2  # both still reported, suppressed


def test_syntax_error_becomes_hvd000_finding():
    fs = lint_source("def broken(:\n    pass\n", path="bad.py")
    assert [f.rule for f in fs] == ["HVD000"]
    assert "could not parse" in fs[0].message
    assert fs[0].severity == "error"


def test_hvd000_respects_select_and_ignore():
    """Parse failures obey the rule filters like any other rule (review
    regression: HVD000 used to bypass --select/--ignore)."""
    bad = "def broken(:\n"
    assert lint_source(bad, select=("HVD001",)) == []
    assert lint_source(bad, ignore=("HVD000",)) == []
    assert [f.rule for f in lint_source(bad, select=("HVD000",))] == \
        ["HVD000"]
    from horovod_tpu.analysis import lint_paths
    assert lint_paths(["/nonexistent/x"], ignore=("HVD000",)) == []


def test_select_and_ignore_filters():
    src = """\
    import jax
    import time

    @jax.jit
    def step(x):
        print(x)
        return x + time.time()
    """
    assert fired(src, select=("HVD008",)) == [("HVD008", 7)]
    assert fired(src, ignore=("HVD008",)) == [("HVD004", 6)]


def test_every_finding_carries_catalogue_metadata():
    src = """\
    import jax
    import time

    @jax.jit
    def step(x):
        return x + time.time()
    """
    (f,) = findings_of(src)
    assert f.severity == RULES[f.rule].severity
    assert f.fix_hint == RULES[f.rule].fix_hint
    assert f.to_dict()["rule"] == f.rule


# ---------------------------------------------------------------------------
# CLI + path walking
# ---------------------------------------------------------------------------

@pytest.fixture()
def corpus_dir(tmp_path):
    (tmp_path / "dirty.py").write_text(textwrap.dedent("""\
        import jax
        import time

        @jax.jit
        def step(x):
            return x + time.time()
        """))
    (tmp_path / "clean.py").write_text("x = 1\n")
    sub = tmp_path / "__pycache__"
    sub.mkdir()
    (sub / "skipme.py").write_text("def broken(:\n")
    return tmp_path


def test_cli_text_output_and_exit_codes(corpus_dir, capsys):
    rc = cli_main([str(corpus_dir)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD008" in out and "dirty.py" in out
    assert "skipme" not in out  # __pycache__ pruned
    rc = cli_main([str(corpus_dir / "clean.py")])
    assert rc == 0


def test_cli_json_output(corpus_dir, capsys):
    rc = cli_main([str(corpus_dir), "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["summary"]["total"] == 1
    assert payload["summary"]["by_rule"] == {"HVD008": 1}
    (f,) = payload["findings"]
    assert f["rule"] == "HVD008" and f["line"] == 6


def test_cli_missing_path_is_a_finding_not_a_crash(capsys):
    rc = cli_main(["/nonexistent/hvdlint/path"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD000" in out and "does not exist" in out


def test_cli_syntax_error_file_nonzero_but_graceful(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    rc = cli_main([str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD000" in out


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in RULES:
        assert rule_id in out


def test_lint_paths_mixed_file_and_dir(corpus_dir):
    fs = lint_paths([str(corpus_dir / "dirty.py"), str(corpus_dir)])
    # deduped: dirty.py linted once even though passed twice
    assert [(f.rule, f.line) for f in unsuppressed(fs)] == [("HVD008", 6)]
