"""ISSUE 5 satellite: fp16/bf16 compression on the fused flat-buffer
eager path.

Before this change, ``compression != none`` forced the eager gradient
path off the single-flat-buffer dispatch onto the per-bucket grouped
path (per-tensor compress/decompress + one grouped collective) —
docs/tensor_fusion.md documented it as the open gap.  Now each
same-dtype fusion bucket packs once, compresses ONCE, and dispatches ONE
collective.  These tests pin:

* parity — the fused-compressed result equals the per-tensor
  compress → reduce → decompress reference exactly (casts are
  elementwise, so compress(concat) == concat(compress));
* dispatch count — one engine dispatch per same-dtype bucket, wire
  payload in the compressed dtype;
* routing — ``_allreduce_tree`` sends compressed multi-leaf eager trees
  through ``_fused_allreduce`` on a multi-process topology (and keeps
  the grouped path in emulated mode).

The engine is faked (single-rank ``single``-path semantics: allreduce of
one participant is the identity up to scale factors), so the data-path
transform — pack → compress → dispatch → decompress → slice — is pinned
hermetically without a multi-process world.
"""

import types

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import ops as _ops
from horovod_tpu.compression import Compression


class _FakeEngine:
    """Records every dispatch; applies the caller's single-participant
    reduction (exactly what EagerEngine.run does at np=1)."""

    def __init__(self):
        self.dispatches = []

    def run(self, kind, body, tensors, sig, single, name=None, **kw):
        self.dispatches.append({
            "kind": kind, "name": name,
            "dtypes": [str(t.dtype) for t in tensors],
            "sizes": [int(t.size) for t in tensors],
        })
        return single(tensors)


@pytest.fixture()
def fake_engine(hvd8, monkeypatch):
    eng = _FakeEngine()
    monkeypatch.setattr(_ops, "_engine", lambda: eng)
    return eng


def _tensors():
    rng = np.random.RandomState(0)
    return [jnp.asarray(rng.randn(*s).astype(np.float32))
            for s in ((4, 3), (7,), (2, 2, 2))]


@pytest.mark.parametrize("comp,wire", [(Compression.fp16, "float16"),
                                       (Compression.bf16, "bfloat16")])
def test_fused_allreduce_compresses_bucket_once(fake_engine, comp, wire):
    ts = _tensors()
    outs = _ops._fused_allreduce(ts, op=hvd.Average, compression=comp,
                                 prescale_factor=2.0)
    # ONE dispatch for the whole bucket, wire payload in the compressed
    # dtype, flat size = sum of the tensors.
    assert len(fake_engine.dispatches) == 1
    d = fake_engine.dispatches[0]
    assert d["dtypes"] == [wire]
    assert d["sizes"] == [sum(int(t.size) for t in ts)]
    assert d["name"].startswith(f"fusedbuf.{wire}.")
    # Parity vs the per-tensor grouped compress path: compress each
    # tensor, apply the (identity-at-np=1) reduction + scale, decompress.
    for t, out in zip(ts, outs):
        wire_t, ctx = comp.compress(t)
        ref = comp.decompress(wire_t.astype(jnp.float32) * 2.0, ctx)
        assert out.dtype == t.dtype and out.shape == t.shape
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_fused_allreduce_none_compression_unchanged(fake_engine):
    ts = _tensors()
    outs = _ops._fused_allreduce(ts, op=hvd.Sum)
    assert len(fake_engine.dispatches) == 1
    assert fake_engine.dispatches[0]["dtypes"] == ["float32"]
    for t, out in zip(ts, outs):
        np.testing.assert_array_equal(np.asarray(out), np.asarray(t))


def test_allreduce_tree_routes_compressed_buckets_through_fused(
        fake_engine, monkeypatch):
    """With a true multi-process topology, a compressed multi-leaf eager
    gradient tree must take the fused path: one dispatch per same-dtype
    fusion bucket (not one per tensor), each on a compressed flat
    buffer."""
    from horovod_tpu import core as _core
    from horovod_tpu import optimizer as opt_mod
    monkeypatch.setattr(
        _core._state, "topology",
        types.SimpleNamespace(size=2, emulated=False))
    rng = np.random.RandomState(1)
    grads = {f"layer_{i}": jnp.asarray(rng.randn(8, 4).astype(np.float32))
             for i in range(6)}
    reduced = opt_mod._allreduce_tree(
        grads, opt_mod.ReduceOp.SUM, Compression.fp16, 1.0, 1.0, None)
    # All six small leaves fit one 128 MB bucket: exactly ONE dispatch,
    # fp16 on the wire.
    assert len(fake_engine.dispatches) == 1
    assert fake_engine.dispatches[0]["dtypes"] == ["float16"]
    for k, g in grads.items():
        ref = g.astype(jnp.float16).astype(jnp.float32)  # wire round-trip
        np.testing.assert_array_equal(np.asarray(reduced[k]),
                                      np.asarray(ref))


def test_custom_compressor_keeps_grouped_path(fake_engine, monkeypatch):
    """A user-defined Compressor subclass is NOT elementwise-guaranteed
    (compress(concat) != concat(compress) for e.g. per-tensor scaling),
    so it must keep the per-tensor grouped dispatch even on a true
    multi-process topology."""
    from horovod_tpu import core as _core
    from horovod_tpu import optimizer as opt_mod
    from horovod_tpu.compression import Compressor

    class _PerTensorScale(Compressor):
        @staticmethod
        def compress(tensor):
            scale = jnp.max(jnp.abs(tensor)) + 1e-9
            return tensor / scale, scale

        @staticmethod
        def decompress(tensor, ctx):
            return tensor * ctx

    monkeypatch.setattr(
        _core._state, "topology",
        types.SimpleNamespace(size=2, emulated=False))
    grads = [jnp.asarray([1.0, 2.0]), jnp.asarray([100.0, 200.0])]
    out = opt_mod._allreduce_tree(
        grads, opt_mod.ReduceOp.SUM, _PerTensorScale, 1.0, 1.0, None)
    assert all(d["kind"] == "grouped_allreduce"
               for d in fake_engine.dispatches)
    for g, o in zip(grads, out):  # per-tensor scales round-trip exactly
        np.testing.assert_allclose(np.asarray(o), np.asarray(g),
                                   rtol=1e-6)


def test_allreduce_tree_emulated_mode_keeps_grouped_path(fake_engine,
                                                         monkeypatch):
    """Emulated topologies must NOT take the flat pack (their tensors
    are per-rank stacks): the grouped dispatch stays."""
    from horovod_tpu import core as _core
    from horovod_tpu import optimizer as opt_mod
    monkeypatch.setattr(
        _core._state, "topology",
        types.SimpleNamespace(size=2, emulated=True))
    grads = [jnp.ones((3,), jnp.float32), jnp.ones((5,), jnp.float32)]
    opt_mod._allreduce_tree(
        grads, opt_mod.ReduceOp.SUM, Compression.fp16, 1.0, 1.0, None)
    assert all(d["kind"] == "grouped_allreduce"
               for d in fake_engine.dispatches)
