"""ISSUE 19: hvdstream structured decoding + logprob scoring.

Pins the grammar-constrained decoding and scoring contracts:

* parse_schema — the supported JSON-Schema subset, with every
  unsupported keyword/shape named in a ValueError (the HTTP 400);
* TokenGrammar — per-feature mask walks (object / array / string /
  number / integer / boolean / enum / const): any token sequence that
  honors ``allowed_mask`` spells a complete conforming document, EOS
  joins the mask exactly at accepting states, ``exhausted`` fires when
  the document admits no continuation, ``matches`` validates offline;
* engine — schema'd requests produce valid documents at temperature 0
  AND under seeded sampling (every seed), finish reason ``grammar``
  when the document completes itself, the paged-capability gate for
  schema/logprobs requests;
* HTTP — ``logprobs: k`` on /generate (buffered body and streamed
  token events), /score per-token logprob parity against the adapter's
  own log-softmax, and the 400 surfaces (unsupported keyword, missing
  eos_id, out-of-range tokens, oversized top_logprobs).
"""

import http.client
import json
import math
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import create_mlp
from horovod_tpu.serve import (InferenceEngine, MLPAdapter, Replica,
                               ReplicaScheduler, Request, ServeMetrics,
                               ServeServer)
from horovod_tpu.serve.streaming import parse_sse
from horovod_tpu.serve.structured import TokenGrammar, parse_schema

EOS = 0
BYTE_VOCAB = [chr(i) for i in range(128)]


# -- harness -----------------------------------------------------------------

def _mlp256(seed=3, max_len=512):
    """Byte-vocabulary MLP: token ids ARE character codes, so grammar
    emissions decode with bytes().decode() (the bench's idiom)."""
    vocab = 256
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return MLPAdapter(mlp, params, vocab_size=vocab, max_len=max_len)


def _paged_engine(adapter=None, **kw):
    kw.setdefault("max_batch", 4)
    return InferenceEngine(adapter or _mlp256(), kv_mode="paged",
                           metrics=ServeMetrics(),
                           replica_id="structured-t", **kw)


def _run(eng, prompt, **req_kw):
    r = Request(prompt, **req_kw)
    eng.batcher.submit(r)
    toks = r.result(timeout=60)
    return r, toks


def _doc(tokens):
    """Decode a byte-vocab completion, dropping a trailing EOS."""
    toks = list(tokens)
    while toks and toks[-1] == EOS:
        toks.pop()
    return bytes(toks).decode()


def _server(adapter_fn=_mlp256, n=1):
    replicas = [Replica(f"replica-{i}", None,
                        _paged_engine(adapter_fn()))
                for i in range(n)]
    sched = ReplicaScheduler(replicas, metrics=replicas[0].engine.metrics)
    server = ServeServer(sched, request_timeout_s=60)
    port = server.start(port=0, host="127.0.0.1")
    return server, sched, port


def _post(port, payload, path="/generate", timeout=30):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _stream_events(port, payload, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/generate",
                     body=json.dumps(payload).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        raw = b""
        while True:
            chunk = resp.read1(8192)
            if not chunk:
                break
            raw += chunk
            cut = raw.rfind(b"\n\n")
            events = parse_sse(raw[:cut + 2]) if cut >= 0 else []
            if events and events[-1][0] in ("done", "error"):
                return events
        return parse_sse(raw)
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# parse_schema: the supported subset, loudly bounded
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("schema,needle", [
    ({"anyOf": [{"type": "string"}]}, "anyOf"),
    ({"type": "object", "patternProperties": {}}, "patternProperties"),
    ({"type": "string", "minLength": 3}, "minLength"),
    ({"type": "tuple"}, "unsupported type"),
    ({"type": "object", "additionalProperties": True},
     "additionalProperties"),
    ({"type": "array"}, "items"),
    ({"type": "array", "items": {"type": "integer"}, "minItems": 5,
      "maxItems": 2}, "maxItems"),
    ({"type": "object", "properties": {}, "required": ["ghost"]},
     "ghost"),
    ({"const": True, "type": "boolean"}, "const"),
    ({"enum": []}, "enum"),
    (True, "boolean"),
    ([1, 2], "JSON object"),
])
def test_parse_schema_names_the_unsupported_piece(schema, needle):
    with pytest.raises(ValueError, match=needle):
        parse_schema(schema)


def test_parse_schema_accepts_the_documented_subset():
    parse_schema({"type": "object",
                  "properties": {"a": {"type": "integer"}},
                  "required": ["a"], "additionalProperties": False})
    parse_schema({"type": "array", "items": {"type": "number"},
                  "minItems": 1, "maxItems": 4})
    for t in ("string", "number", "integer", "boolean", "null"):
        parse_schema({"type": t})
    parse_schema({"enum": ["red", 3, None]})
    parse_schema({"const": {"x": 1}})


# ---------------------------------------------------------------------------
# TokenGrammar: masked walks spell conforming documents
# ---------------------------------------------------------------------------

def _constrained_walk(g, rng, max_steps=2000):
    """Random walk honoring ``allowed_mask``; ends on ``exhausted`` or
    by drawing EOS where the mask admits it.  Returns the token list
    (EOS excluded)."""
    state, toks = g.start, []
    for _ in range(max_steps):
        if g.exhausted(state):
            return toks
        mask = g.allowed_mask(state)
        if mask[g.eos_id] and rng.rand() < 0.6:
            return toks  # EOS is only maskable at accepting states
        allowed = np.flatnonzero(mask)
        allowed = allowed[allowed != g.eos_id]
        assert allowed.size, "live non-exhausted state with no moves"
        tok = int(allowed[rng.randint(0, allowed.size)])
        toks.append(tok)
        state = g.advance_token(state, tok)
        assert state, "mask admitted a killing token"
    raise AssertionError("walk did not terminate")


def _validate(doc, schema):
    t = schema.get("type")
    if "const" in schema:
        assert doc == schema["const"]
    elif "enum" in schema:
        assert doc in schema["enum"]
    elif t == "object":
        assert isinstance(doc, dict)
        assert set(doc) <= set(schema.get("properties", {}))
        for name in schema.get("required", []):
            assert name in doc
        for name, sub in schema.get("properties", {}).items():
            if name in doc:
                _validate(doc[name], sub)
    elif t == "array":
        assert isinstance(doc, list)
        assert len(doc) >= schema.get("minItems", 0)
        if "maxItems" in schema:
            assert len(doc) <= schema["maxItems"]
        for item in doc:
            _validate(item, schema["items"])
    elif t == "string":
        assert isinstance(doc, str)
    elif t == "integer":
        assert isinstance(doc, int) and not isinstance(doc, bool)
    elif t == "number":
        assert isinstance(doc, (int, float)) \
            and not isinstance(doc, bool)
    elif t == "boolean":
        assert isinstance(doc, bool)
    elif t == "null":
        assert doc is None


@pytest.mark.parametrize("schema", [
    {"type": "object",
     "properties": {"a": {"type": "integer"},
                    "b": {"type": "boolean"},
                    "c": {"type": "string"}},
     "required": ["a"], "additionalProperties": False},
    {"type": "array", "items": {"type": "integer"},
     "minItems": 1, "maxItems": 3},
    {"type": "array", "items": {"type": "boolean"}, "minItems": 0,
     "maxItems": 2},
    {"type": "string"},
    {"type": "number"},
    {"type": "integer"},
    {"type": "boolean"},
    {"type": "null"},
    {"enum": ["red", "green", 3]},
    {"const": {"x": 1, "y": [True]}},
], ids=["object", "array", "array-empty-ok", "string", "number",
        "integer", "boolean", "null", "enum", "const"])
def test_grammar_masked_walks_spell_conforming_documents(schema):
    g = TokenGrammar(schema, BYTE_VOCAB, eos_id=EOS)
    rng = np.random.RandomState(7)
    for trial in range(20):
        toks = _constrained_walk(g, rng)
        assert g.matches(toks), toks
        assert g.matches(toks + [EOS])  # trailing EOS accepted
        doc = json.loads("".join(BYTE_VOCAB[t] for t in toks))
        _validate(doc, schema)


def test_grammar_eos_masked_in_only_at_accepting_states():
    g = TokenGrammar({"const": True}, BYTE_VOCAB, eos_id=EOS)
    state = g.start
    for i, ch in enumerate("true"):
        mask = g.allowed_mask(state)
        assert not mask[EOS], f"EOS allowed mid-emission at {i}"
        assert not g.accepting(state)
        # The const admits exactly one continuation per step.
        assert int(mask.sum()) == 1 and mask[ord(ch)]
        state = g.advance_token(state, ord(ch))
    assert g.accepting(state)
    assert g.allowed_mask(state)[EOS]
    assert g.exhausted(state)  # nothing but EOS left -> reason grammar


def test_grammar_const_and_enum_emit_canonical_json():
    g = TokenGrammar({"const": {"x": 1, "y": [True]}}, BYTE_VOCAB,
                     eos_id=EOS)
    toks = _constrained_walk(g, np.random.RandomState(0))
    # Canonical: compact separators, key order as given.
    assert "".join(BYTE_VOCAB[t] for t in toks) == '{"x":1,"y":[true]}'
    g = TokenGrammar({"enum": ["red", 3]}, BYTE_VOCAB, eos_id=EOS)
    seen = set()
    rng = np.random.RandomState(1)
    for _ in range(30):
        seen.add("".join(BYTE_VOCAB[t]
                         for t in _constrained_walk(g, rng)))
    assert seen == {'"red"', "3"}


def test_grammar_matches_rejects_tampered_and_truncated():
    g = TokenGrammar({"const": True}, BYTE_VOCAB, eos_id=EOS)
    good = [ord(c) for c in "true"]
    assert g.matches(good)
    assert not g.matches(good[:-1])          # incomplete document
    assert not g.matches(good + [ord("x")])  # trailing garbage
    bad = list(good)
    bad[1] = ord("x")
    assert not g.matches(bad)                # tampered interior
    assert not g.matches([EOS])              # EOS before acceptance
    assert not g.matches(good[:2] + [EOS] + good[2:])  # EOS mid-doc


def test_grammar_requires_byte_transparent_vocab_and_valid_eos():
    # eos out of vocabulary range: disabled, masks never include it.
    g = TokenGrammar({"type": "boolean"}, BYTE_VOCAB, eos_id=9999)
    assert g.eos_id is None


# ---------------------------------------------------------------------------
# engine: constrained decoding through the real paged pipeline
# ---------------------------------------------------------------------------

BOOL_SCHEMA = {"type": "boolean"}
OBJ_SCHEMA = {"type": "object",
              "properties": {"ok": {"type": "boolean"}},
              "required": ["ok"], "additionalProperties": False}


def test_engine_schema_greedy_and_sampled_always_valid():
    eng = _paged_engine().start()
    g = TokenGrammar(OBJ_SCHEMA, [chr(i) for i in range(256)],
                     eos_id=EOS)
    try:
        r, toks = _run(eng, [65, 66, 67], max_new_tokens=64,
                       eos_id=EOS, schema=OBJ_SCHEMA)
        doc = json.loads(_doc(toks))
        assert isinstance(doc.get("ok"), bool) and set(doc) <= {"ok"}
        assert g.matches([t for t in toks if t != EOS])
        # Sampled: every seed stays inside the grammar.
        for seed in range(8):
            r, toks = _run(eng, [70 + seed], max_new_tokens=64,
                           eos_id=EOS, temperature=1.0,
                           seed=1000 + seed, schema=OBJ_SCHEMA)
            doc = json.loads(_doc(toks))
            assert isinstance(doc.get("ok"), bool), (seed, toks)
            assert set(doc) <= {"ok"}
            assert r.finish_reason in ("grammar", "stop")
    finally:
        eng.stop()


def test_engine_exhausted_grammar_finishes_with_reason_grammar():
    eng = _paged_engine().start()
    try:
        r, toks = _run(eng, [65], max_new_tokens=64, eos_id=EOS,
                       temperature=0.9, seed=5, schema=BOOL_SCHEMA)
        assert _doc(toks) in ("true", "false")
        # "true"/"false" admits no continuation: the engine finished
        # the sequence itself instead of waiting for the model's EOS.
        assert r.finish_reason == "grammar"
        assert len(toks) <= 6
    finally:
        eng.stop()


def test_engine_schema_needs_paged_sampling_capable_stack():
    eng = InferenceEngine(_mlp256(), max_batch=2, kv_mode="slot",
                          metrics=ServeMetrics(),
                          replica_id="slot-t").start()
    try:
        r = Request([65], max_new_tokens=8, eos_id=EOS,
                    schema=BOOL_SCHEMA)
        eng.batcher.submit(r)
        with pytest.raises(ValueError, match="paged"):
            r.result(timeout=30)
    finally:
        eng.stop()


def test_engine_logprobs_report_model_belief_with_topk():
    ad = _mlp256()
    eng = _paged_engine(ad).start()
    try:
        r, toks = _run(eng, [5, 7], max_new_tokens=6, logprobs=3)
        entries = r.token_logprobs
        assert len(entries) == len(toks)
        # Markov chain: each row's distribution depends only on the
        # previous token (the last prompt token for position 0).
        context = [7] + toks[:-1]
        for ctx_tok, tok, entry in zip(context, toks, entries):
            assert entry["token"] == tok
            row = np.asarray(
                ad._logits_of(np.asarray([ctx_tok], np.int32)),
                np.float64)[0]
            lse = float(row.max()) + math.log(
                float(np.sum(np.exp(row - row.max()))))
            assert entry["logprob"] == pytest.approx(
                float(row[tok] - lse), rel=1e-5)
            top = entry["top"]
            assert len(top) == 3
            lps = [t["logprob"] for t in top]
            assert lps == sorted(lps, reverse=True)
            # Greedy decode: the chosen token IS the top-1.
            assert top[0]["token"] == tok
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# HTTP: schema + logprobs + /score
# ---------------------------------------------------------------------------

def test_http_schema_stream_matches_buffered_and_validates():
    server, _, port = _server()
    try:
        payload = {"tokens": [65, 66], "max_new_tokens": 64,
                   "eos_id": EOS, "temperature": 0.8, "seed": 42,
                   "schema": OBJ_SCHEMA}
        status, buffered = _post(port, payload)
        assert status == 200
        doc = json.loads(_doc(buffered["tokens"]))
        assert isinstance(doc.get("ok"), bool)
        events = _stream_events(port, dict(payload, stream=True))
        assert events[-1][0] == "done"
        streamed = [t for e in events if e[0] == "token"
                    for t in e[1]["tokens"]]
        assert streamed == buffered["tokens"]
        assert events[-1][1]["finish_reason"] == \
            buffered["finish_reason"]
    finally:
        server.stop()


def test_http_generate_rejects_unsupported_schema_keyword():
    server, _, port = _server()
    try:
        status, body = _post(port, {
            "tokens": [65], "eos_id": EOS,
            "schema": {"anyOf": [{"type": "boolean"}]}})
        assert status == 400
        assert "anyOf" in body["error"]
        status, body = _post(port, {
            "tokens": [65], "schema": BOOL_SCHEMA})  # no eos_id
        assert status == 400
        assert "eos_id" in body["error"]
    finally:
        server.stop()


def test_http_generate_logprobs_ride_body_and_stream_events():
    server, _, port = _server()
    try:
        payload = {"tokens": [5, 7], "max_new_tokens": 5, "logprobs": 2}
        status, buffered = _post(port, payload)
        assert status == 200
        entries = buffered["logprobs"]
        assert len(entries) == len(buffered["tokens"])
        for tok, entry in zip(buffered["tokens"], entries):
            assert entry["token"] == tok
            assert entry["logprob"] <= 0.0
            assert len(entry["top"]) == 2
        # Streamed: per-token logprobs arrive ON the token events.
        events = _stream_events(port, dict(payload, stream=True))
        streamed = [lp for e in events if e[0] == "token"
                    for lp in e[1]["logprobs"]]
        assert streamed == entries
        assert events[-1][1]["logprobs"] == entries
    finally:
        server.stop()


def test_http_score_parity_with_adapter_log_softmax():
    ad = _mlp256()
    server, _, port = _server(lambda: ad)
    try:
        tokens = [5, 7, 11, 2]
        status, body = _post(port, {"tokens": tokens,
                                    "top_logprobs": 3}, path="/score")
        assert status == 200
        assert body["tokens"] == tokens
        entries = body["logprobs"]
        assert len(entries) == len(tokens)
        assert entries[0] is None  # nothing conditions position 0
        logits = np.asarray(ad.score_logits(tokens), np.float64)
        for p in range(1, len(tokens)):
            row = logits[p - 1]
            lse = float(row.max()) + math.log(
                float(np.sum(np.exp(row - row.max()))))
            want = float(row[tokens[p]] - lse)
            assert entries[p]["token"] == tokens[p]
            assert entries[p]["logprob"] == pytest.approx(want,
                                                          rel=1e-5)
            top = entries[p]["top"]
            assert len(top) == 3
            assert top[0]["logprob"] >= entries[p]["logprob"]
        # Scoring is pure observation: no decode slots were consumed.
        status, again = _post(port, {"tokens": tokens}, path="/score")
        assert status == 200 and "top" not in (again["logprobs"][1]
                                               or {})
    finally:
        server.stop()


def test_http_score_validation_400s():
    server, _, port = _server()
    try:
        for payload, needle in [
            ({"tokens": [5, 999]}, "out of range"),
            ({"tokens": [5], "top_logprobs": 17}, "top_logprobs"),
            ({"tokens": []}, "non-empty"),
            ({"tokens": "nope"}, "non-empty"),
        ]:
            status, body = _post(port, payload, path="/score")
            assert status == 400, payload
            assert needle in body["error"], (payload, body)
    finally:
        server.stop()
