"""In-jit (SPMD) collective numerics over an 8-device mesh.

Mirrors the reference's parallel suite pattern (test/parallel/test_torch.py,
test_tensorflow.py): compute the expected value locally per rank and compare —
here the "ranks" are mesh slots and the collective runs inside shard_map so it
exercises the real XLA collective lowering.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.compat import has_vma_tracking
from horovod_tpu.ops import collective_ops as C

N = 8


def run_spmd(hvd_mod, body, *stacked, in_specs=None, out_specs=None):
    """shard_map `body` over the mesh; stacked inputs/outputs [N, ...]."""
    mesh = hvd_mod.mesh()
    in_specs = in_specs or tuple(P("hvd") for _ in stacked)

    def inner(*xs):
        outs = body(*(x[0] for x in xs))
        if not isinstance(outs, tuple):
            outs = (outs,)
        return tuple(o[None] for o in outs)

    res = jax.jit(jax.shard_map(inner, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs or P("hvd")))(*stacked)
    return res if len(res) > 1 else res[0]


@pytest.fixture()
def per_rank():
    rng = np.random.RandomState(42)
    return jnp.asarray(rng.randn(N, 4, 3).astype(np.float32))


def test_allreduce_sum(hvd8, per_rank):
    out = run_spmd(hvd8, lambda x: C.allreduce(x, C.Sum), per_rank)
    expected = np.sum(np.asarray(per_rank), axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)


def test_allreduce_average(hvd8, per_rank):
    out = run_spmd(hvd8, lambda x: C.allreduce(x, C.Average), per_rank)
    expected = np.mean(np.asarray(per_rank), axis=0)
    np.testing.assert_allclose(out[0], expected, rtol=1e-5)
    np.testing.assert_allclose(out[7], expected, rtol=1e-5)


@pytest.mark.parametrize("op,npop", [(C.Min, np.min), (C.Max, np.max),
                                     (C.Product, np.prod)])
def test_allreduce_minmaxprod(hvd8, per_rank, op, npop):
    out = run_spmd(hvd8, lambda x: C.allreduce(x, op), per_rank)
    expected = npop(np.asarray(per_rank), axis=0)
    np.testing.assert_allclose(out[3], expected, rtol=1e-5)


def test_allreduce_int_dtypes(hvd8):
    x = jnp.asarray(np.arange(N * 4).reshape(N, 4).astype(np.int32))
    out = run_spmd(hvd8, lambda t: C.allreduce(t, C.Sum), x)
    np.testing.assert_array_equal(out[0], np.sum(np.asarray(x), axis=0))
    out = run_spmd(hvd8, lambda t: C.allreduce(t, C.Average), x)
    np.testing.assert_array_equal(
        out[0], np.sum(np.asarray(x), axis=0) // N)


def test_allreduce_bf16(hvd8):
    x = jnp.ones((N, 16), jnp.bfloat16)
    out = run_spmd(hvd8, lambda t: C.allreduce(t, C.Sum), x)
    np.testing.assert_allclose(np.asarray(out[0], np.float32), 8.0)


def test_allreduce_prescale_postscale(hvd8, per_rank):
    out = run_spmd(
        hvd8, lambda x: C.allreduce(x, C.Sum, prescale_factor=0.5,
                                    postscale_factor=3.0), per_rank)
    expected = 3.0 * np.sum(0.5 * np.asarray(per_rank), axis=0)
    np.testing.assert_allclose(out[0], expected, rtol=1e-5)


def test_allreduce_subset(hvd8, per_rank):
    members = (1, 3, 5)
    out = run_spmd(hvd8, lambda x: C.allreduce(x, C.Sum, members=members),
                   per_rank)
    arr = np.asarray(per_rank)
    expected = arr[list(members)].sum(axis=0)
    for r in members:
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)
    for r in set(range(N)) - set(members):
        np.testing.assert_allclose(out[r], arr[r], rtol=1e-6)


def test_allreduce_subset_min(hvd8, per_rank):
    members = (0, 2)
    out = run_spmd(hvd8, lambda x: C.allreduce(x, C.Min, members=members),
                   per_rank)
    arr = np.asarray(per_rank)
    np.testing.assert_allclose(out[0], arr[[0, 2]].min(axis=0), rtol=1e-6)
    np.testing.assert_allclose(out[5], arr[5], rtol=1e-6)


def test_grouped_allreduce(hvd8):
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(N, 5).astype(np.float32))
    b = jnp.asarray(rng.randn(N, 2, 2).astype(np.float32))

    def body(x, y):
        return tuple(C.grouped_allreduce([x, y], C.Average))

    oa, ob = run_spmd(hvd8, body, a, b)
    np.testing.assert_allclose(oa[0], np.mean(np.asarray(a), 0), rtol=1e-5)
    np.testing.assert_allclose(ob[0], np.mean(np.asarray(b), 0), rtol=1e-5)


def test_allgather(hvd8, per_rank):
    out = run_spmd(hvd8, lambda x: C.allgather(x), per_rank)
    expected = np.asarray(per_rank).reshape(N * 4, 3)
    for r in (0, 4, 7):
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


def test_allgather_subset(hvd8, per_rank):
    members = (2, 6)
    out = run_spmd(hvd8, lambda x: C.allgather(x, members=members), per_rank)
    arr = np.asarray(per_rank)
    expected = np.concatenate([arr[2], arr[6]], axis=0)
    np.testing.assert_allclose(out[2], expected, rtol=1e-6)
    np.testing.assert_allclose(out[6], expected, rtol=1e-6)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(hvd8, per_rank, root):
    out = run_spmd(hvd8, lambda x: C.broadcast(x, root), per_rank)
    expected = np.asarray(per_rank)[root]
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


def test_broadcast_bool(hvd8):
    x = jnp.asarray(np.arange(N * 3).reshape(N, 3) % 2 == 0)
    out = run_spmd(hvd8, lambda t: C.broadcast(t, 2), x)
    assert out.dtype == jnp.bool_
    np.testing.assert_array_equal(out[5], np.asarray(x)[2])


def test_broadcast_subset_relative_root(hvd8, per_rank):
    members = (4, 5, 6)
    # set-relative root 1 → global slot 5
    out = run_spmd(hvd8, lambda x: C.broadcast(x, 1, members=members),
                   per_rank)
    arr = np.asarray(per_rank)
    for r in members:
        np.testing.assert_allclose(out[r], arr[5], rtol=1e-6)
    np.testing.assert_allclose(out[0], arr[0], rtol=1e-6)


def test_alltoall(hvd8):
    # rank r sends block j to rank j; classic transpose check.
    x = jnp.asarray(
        np.arange(N * N * 2).reshape(N, N, 2).astype(np.float32))
    out = run_spmd(hvd8, lambda t: C.alltoall(t), x)
    arr = np.asarray(x)
    for r in (0, 3, 7):
        expected = np.stack([arr[src, r] for src in range(N)], axis=0)
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


def test_alltoall_subset(hvd8):
    members = (1, 2, 5, 6)
    k = len(members)
    x = jnp.asarray(np.arange(N * k * 3).reshape(N, k, 3).astype(np.float32))
    out = run_spmd(hvd8, lambda t: C.alltoall(t, members=members), x)
    arr = np.asarray(x)
    for j, r in enumerate(members):
        expected = np.stack([arr[src, j] for src in members], axis=0)
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


def test_reducescatter_even(hvd8):
    x = jnp.asarray(np.random.RandomState(1).randn(N, 16, 3).astype(np.float32))
    out = run_spmd(hvd8, lambda t: C.reducescatter(t, C.Sum), x)
    total = np.sum(np.asarray(x), axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], total[r * 2:(r + 1) * 2], rtol=1e-5)


def test_reducescatter_uneven_padded(hvd8):
    # dim0=10 over 8 slots → padded to 16, block 2 each; reference gives the
    # first 10%8=2 ranks an extra row instead (collective_operations.cc).
    x = jnp.asarray(np.random.RandomState(2).randn(N, 10, 2).astype(np.float32))
    out = run_spmd(hvd8, lambda t: C.reducescatter(t, C.Sum), x)
    total = np.sum(np.asarray(x), axis=0)
    padded = np.concatenate([total, np.zeros((6, 2), np.float32)], axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], padded[r * 2:(r + 1) * 2],
                                   rtol=1e-5, atol=1e-6)


def test_reducescatter_average(hvd8):
    x = jnp.ones((N, 8, 2), jnp.float32)
    out = run_spmd(hvd8, lambda t: C.reducescatter(t, C.Average), x)
    np.testing.assert_allclose(out[0], np.ones((1, 2)), rtol=1e-6)


def test_reducescatter_subset(hvd8):
    members = (0, 4)
    x = jnp.asarray(np.random.RandomState(3).randn(N, 6, 2).astype(np.float32))
    out = run_spmd(hvd8, lambda t: C.reducescatter(t, C.Sum, members=members),
                   x)
    arr = np.asarray(x)
    total = arr[[0, 4]].sum(axis=0)  # [6,2] over 2 members → blocks of 3
    np.testing.assert_allclose(out[0], total[0:3], rtol=1e-5)
    np.testing.assert_allclose(out[4], total[3:6], rtol=1e-5)


def test_barrier_in_jit(hvd8):
    out = run_spmd(hvd8, lambda: (C.barrier(),), out_specs=P("hvd"))
    np.testing.assert_array_equal(np.asarray(out), np.zeros((N,), np.int32))


# -- gradients: the reference registers these by hand
#    (tensorflow/mpi_ops.py:115-537); here they fall out of differentiability.

@pytest.mark.skipif(
    not has_vma_tracking(),
    reason="psum's transpose is only the Horovod gradient table under vma "
           "tracking; old jax re-sums the cotangent (see compat.py)")
def test_allreduce_gradient_is_allreduce(hvd8, per_rank):
    def body(x):
        def loss(t):
            return jnp.sum(C.allreduce(t, C.Sum) ** 2)
        return jax.grad(loss)(x)

    out = run_spmd(hvd8, body, per_rank)
    reduced = np.sum(np.asarray(per_rank), axis=0)
    # d/dx_r sum_ranks(sum(reduced^2)) with per-rank loss: grad = 2*reduced
    # allreduced again → N * 2 * reduced... each rank's loss is local, so
    # grad_r = 2*reduced (psum transpose distributes cotangent).
    for r in range(N):
        np.testing.assert_allclose(out[r], 2 * reduced, rtol=1e-4)


def test_broadcast_gradient_reduces_to_root(hvd8, per_rank):
    root = 2

    def body(x):
        def loss(t):
            return jnp.sum(C.broadcast(t, root) * (1.0 + lax.axis_index("hvd")))
        return jax.grad(loss)(x)

    out = run_spmd(hvd8, body, per_rank)
    # Each rank r computes sum(b * (1+r)); cotangent w.r.t. root's tensor is
    # sum_r (1+r) = 36; non-root grads are zero.
    np.testing.assert_allclose(out[root],
                               36.0 * np.ones_like(out[root]), rtol=1e-5)
    for r in set(range(N)) - {root}:
        np.testing.assert_allclose(out[r], np.zeros_like(out[r]), atol=1e-6)


def test_allreduce_product_subset_ring(hvd8):
    """PRODUCT over a member subset (ring-reduce lowering): members see the
    member-product, non-members keep their input (no O(N·|x|) gather)."""
    members = (0, 3, 4)
    vals = np.arange(2, 2 + N).astype(np.float32)  # [2..9]
    x = jnp.asarray(np.stack([np.full((4,), v) for v in vals]))
    out = run_spmd(hvd8, lambda t: C.allreduce(t, C.Product,
                                               members=members), x)
    expected = np.prod(vals[list(members)])
    for r in members:
        np.testing.assert_allclose(out[r], np.full((4,), expected), rtol=1e-5)
    for r in set(range(N)) - set(members):
        np.testing.assert_allclose(out[r], np.asarray(x)[r], rtol=1e-6)


def test_allreduce_product_int_exact(hvd8):
    """Ring-reduce PRODUCT stays exact for integers (a log-exp lowering
    would not)."""
    x = jnp.asarray(np.full((N, 3), 2, dtype=np.int64))
    out = run_spmd(hvd8, lambda t: C.allreduce(t, C.Product), x)
    np.testing.assert_array_equal(out[0], np.full((3,), 2 ** N))


def test_alltoall_subset_multiblock(hvd8):
    """Subset alltoall with multi-row blocks (dim0 = 2k): ppermute ring
    must deliver whole blocks in member order."""
    members = (0, 2, 5, 7)
    k = len(members)
    x = jnp.asarray(
        np.arange(N * 2 * k * 2).reshape(N, 2 * k, 2).astype(np.float32))
    out = run_spmd(hvd8, lambda t: C.alltoall(t, members=members), x)
    arr = np.asarray(x)
    for j, r in enumerate(members):
        expected = np.concatenate(
            [arr[src, 2 * j:2 * (j + 1)] for src in members], axis=0)
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)
