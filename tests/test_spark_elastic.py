"""horovod_tpu.spark.run_elastic — elastic training over a Spark-style task
pool (reference: horovod/spark/runner.py:312 run_elastic).

No pyspark in the image, so the task pool is threads running the REAL
task_pool_loop (register/heartbeat/launch-subprocess protocol); only the
``_spark_task_pool`` RDD adapter goes unexercised — the same split the
reference uses when it tests elastic-on-Spark through fake task services.
"""

import os
import threading

import pytest

from horovod_tpu.spark.elastic import (SparkTaskPoolDiscovery,
                                       run_elastic, task_pool_loop)


def thread_pool_factory(hostnames=None):
    """Task pool of threads on fake hostnames (default: all on one host)."""

    def factory(num_tasks, addr, port):
        threads = []
        for i in range(num_tasks):
            host = (hostnames or ["node0"] * num_tasks)[i]
            t = threading.Thread(target=task_pool_loop,
                                 args=(addr, port, i),
                                 kwargs={"hostname": host},
                                 daemon=True, name=f"se-task-{i}")
            t.start()
            threads.append(t)

        def join(timeout=30.0):
            for t in threads:
                t.join(timeout)

        return join

    return factory


def make_report_rank():
    """Closure, not a module-level fn: cloudpickle serializes closures by
    VALUE, which the worker subprocess needs (the tests module is not
    importable there)."""

    def fn():
        import os as _os
        return (int(_os.environ["HOROVOD_RANK"]),
                int(_os.environ["HOROVOD_SIZE"]))

    return fn


def make_crash_once(path):
    """Rank 0's FIRST incarnation dies abruptly; every retry succeeds."""

    def fn():
        import os as _os
        if _os.environ["HOROVOD_RANK"] == "0" and not _os.path.exists(path):
            open(path, "w").write("crashed")
            _os._exit(3)
        return (int(_os.environ["HOROVOD_RANK"]),
                int(_os.environ["HVD_TPU_WORLD_VERSION"]))

    return fn


@pytest.mark.integration
def test_run_elastic_happy_path():
    results = run_elastic(make_report_rank(), num_proc=2, min_num_proc=2,
                          start_timeout=60, elastic_timeout=60,
                          _task_pool_factory=thread_pool_factory())
    assert results == [(0, 2), (1, 2)]


@pytest.mark.integration
def test_run_elastic_task_failure_then_rejoin(tmp_path):
    """A crashed worker incarnation (os._exit inside fn) must trigger a
    reset and relaunch on the surviving task pool; the final world's
    results are complete (spark/runner.py:312 + elastic retry contract)."""
    marker = str(tmp_path / "crashed_once")
    results = run_elastic(make_crash_once(marker), num_proc=2,
                          min_num_proc=2, start_timeout=60,
                          elastic_timeout=60, reset_limit=3,
                          _task_pool_factory=thread_pool_factory())
    assert os.path.exists(marker), "first incarnation never ran"
    ranks = [r for r, _ver in results]
    vers = {ver for _r, ver in results}
    assert ranks == [0, 1]
    assert vers == {max(vers)} and max(vers) >= 1, \
        f"expected a post-reset world, got versions {vers}"


@pytest.mark.integration
def test_run_elastic_multi_host_assignment():
    """Tasks on two fake hosts: ranks spread across hosts, local ranks
    correct."""
    results = run_elastic(
        make_report_rank(), num_proc=2, min_num_proc=2,
        start_timeout=60, elastic_timeout=60,
        _task_pool_factory=thread_pool_factory(["nodeA", "nodeB"]))
    assert results == [(0, 2), (1, 2)]


def test_discovery_groups_by_host_and_windows_heartbeats():
    import json
    import time
    recs = {
        "task/0": json.dumps({"host": "a", "ts": time.time()}).encode(),
        "task/1": json.dumps({"host": "a", "ts": time.time()}).encode(),
        "task/2": json.dumps({"host": "b", "ts": time.time()}).encode(),
        "task/3": json.dumps({"host": "b",
                              "ts": time.time() - 999}).encode(),
        "unrelated": b"x",
    }
    d = SparkTaskPoolDiscovery(lambda: recs)
    assert d.find_available_hosts_and_slots() == {"a": 2, "b": 1}
    assert d.task_for_slot("a", 1) == 1
    assert d.task_for_slot("b", 0) == 2
    assert d.task_for_slot("b", 1) is None
