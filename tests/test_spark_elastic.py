"""horovod_tpu.spark.run_elastic — elastic training over a Spark-style task
pool (reference: horovod/spark/runner.py:312 run_elastic).

No pyspark in the image, so the task pool is threads running the REAL
task_pool_loop (register/heartbeat/launch-subprocess protocol); only the
``_spark_task_pool`` RDD adapter goes unexercised — the same split the
reference uses when it tests elastic-on-Spark through fake task services.
"""

import os
import threading

import pytest

from horovod_tpu.spark.elastic import (SparkTaskPoolDiscovery,
                                       run_elastic, task_pool_loop)

# Serialize with the other subprocess-world e2e files (conftest
# pytest_collection_modifyitems): overlapping multi-process worlds on one
# host core cascade spurious stall timeouts.
pytestmark = pytest.mark.xdist_group("heavy_e2e")


def thread_pool_factory(hostnames=None):
    """Task pool of threads on fake hostnames (default: all on one host)."""

    def factory(num_tasks, addr, port):
        threads = []
        for i in range(num_tasks):
            host = (hostnames or ["node0"] * num_tasks)[i]
            t = threading.Thread(target=task_pool_loop,
                                 args=(addr, port, i),
                                 kwargs={"hostname": host},
                                 daemon=True, name=f"se-task-{i}")
            t.start()
            threads.append(t)

        def join(timeout=30.0):
            for t in threads:
                t.join(timeout)

        return join

    return factory


def make_report_rank():
    """Closure, not a module-level fn: cloudpickle serializes closures by
    VALUE, which the worker subprocess needs (the tests module is not
    importable there)."""

    def fn():
        import os as _os
        return (int(_os.environ["HOROVOD_RANK"]),
                int(_os.environ["HOROVOD_SIZE"]))

    return fn


def make_crash_once(path):
    """Rank 0's FIRST incarnation dies abruptly; every retry succeeds."""

    def fn():
        import os as _os
        if _os.environ["HOROVOD_RANK"] == "0" and not _os.path.exists(path):
            open(path, "w").write("crashed")
            _os._exit(3)
        return (int(_os.environ["HOROVOD_RANK"]),
                int(_os.environ["HVD_TPU_WORLD_VERSION"]))

    return fn


@pytest.mark.integration
def test_run_elastic_happy_path():
    results = run_elastic(make_report_rank(), num_proc=2, min_num_proc=2,
                          start_timeout=60, elastic_timeout=60,
                          _task_pool_factory=thread_pool_factory())
    assert results == [(0, 2), (1, 2)]


@pytest.mark.integration
def test_run_elastic_task_failure_then_rejoin(tmp_path):
    """A crashed worker incarnation (os._exit inside fn) must trigger a
    reset and relaunch on the surviving task pool; the final world's
    results are complete (spark/runner.py:312 + elastic retry contract)."""
    marker = str(tmp_path / "crashed_once")
    results = run_elastic(make_crash_once(marker), num_proc=2,
                          min_num_proc=2, start_timeout=60,
                          elastic_timeout=60, reset_limit=3,
                          _task_pool_factory=thread_pool_factory())
    assert os.path.exists(marker), "first incarnation never ran"
    ranks = [r for r, _ver in results]
    vers = {ver for _r, ver in results}
    assert ranks == [0, 1]
    assert vers == {max(vers)} and max(vers) >= 1, \
        f"expected a post-reset world, got versions {vers}"


@pytest.mark.integration
def test_run_elastic_multi_host_assignment():
    """Tasks on two fake hosts: ranks spread across hosts, local ranks
    correct."""
    results = run_elastic(
        make_report_rank(), num_proc=2, min_num_proc=2,
        start_timeout=60, elastic_timeout=60,
        _task_pool_factory=thread_pool_factory(["nodeA", "nodeB"]))
    assert results == [(0, 2), (1, 2)]


@pytest.mark.integration
def test_rescheduled_incarnation_resumes_at_driver_counter(tmp_path):
    """A Spark-rescheduled task incarnation restarts task_pool_loop at
    seq=0 while the driver's launch counter is ahead and the consumed
    launches' cmd records are gone.  The loop must reconcile forward via
    the next/{task} pointer and serve the next launch instead of
    long-polling cmd/{task}/0 forever (round-3 advisor finding)."""
    import json
    import time

    import cloudpickle

    from horovod_tpu.runner.http_server import (KVStoreClient,
                                                RendezvousServer)
    from horovod_tpu.spark import elastic as se

    server = RendezvousServer()
    port = server.start()
    client = KVStoreClient("127.0.0.1", port)
    out = str(tmp_path / "ran")
    try:
        def fn():
            open(out, "w").write("ok")
            return 0

        client.put(se._SCOPE_FN, "blob", cloudpickle.dumps((fn, (), {})))
        # History: launches 0..2 were consumed (cmd deleted, next=3).
        client.put(se._SCOPE_LAUNCH, "next/0", b"3")

        th = threading.Thread(target=task_pool_loop,
                              args=("127.0.0.1", port, 0),
                              daemon=True, name="se-task-reinc")
        th.start()
        # Give the fresh incarnation a moment to start polling at seq=0,
        # then publish the post-reshape launch at the driver's counter.
        time.sleep(1.5)
        env = {"HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
               "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
               "HVD_TPU_WORLD_VERSION": "1", "HOROVOD_RANK": "0"}
        client.put(se._SCOPE_LAUNCH, "cmd/0/3",
                   json.dumps({"env": env}).encode())
        client.put(se._SCOPE_LAUNCH, "next/0", b"4")

        deadline = time.time() + 45
        done = None
        while time.time() < deadline and done is None:
            done = client.get(se._SCOPE_DONE, "done/0/3")
            time.sleep(0.25)
        assert done is not None, \
            "rescheduled incarnation never served the seq-3 launch"
        assert json.loads(done)["code"] == 0
        assert os.path.exists(out)
    finally:
        client.put(se._SCOPE_CTL, "shutdown", b"1")
        th.join(timeout=10)
        server.stop()


def test_discovery_groups_by_host_and_windows_heartbeats():
    import json
    import time
    recs = {
        "task/0": json.dumps({"host": "a", "ts": time.time()}).encode(),
        "task/1": json.dumps({"host": "a", "ts": time.time()}).encode(),
        "task/2": json.dumps({"host": "b", "ts": time.time()}).encode(),
        "task/3": json.dumps({"host": "b",
                              "ts": time.time() - 999}).encode(),
        "unrelated": b"x",
    }
    d = SparkTaskPoolDiscovery(lambda: recs)
    assert d.find_available_hosts_and_slots() == {"a": 2, "b": 1}
    assert d.task_for_slot("a", 1) == 1
    assert d.task_for_slot("b", 0) == 2
    assert d.task_for_slot("b", 1) is None
