"""Self-lint regression gate: the repo must stay hvdlint-clean.

Runs the AST linter in-process over ``horovod_tpu/`` and ``examples/``
(the same paths the dogfooding command ``python -m horovod_tpu.analysis
horovod_tpu examples`` covers) and fails on ANY unsuppressed finding —
so a new rank-guarded collective, swallowed-collective try/except,
unseeded-randomness-in-traced-code, etc. anywhere in the framework or
its examples fails tier-1 instead of wedging a job at runtime.

To silence a deliberate pattern, add ``# hvdlint: disable=HVDxxx`` on
the flagged line WITH a reasoned comment (docs/static_analysis.md).
"""

import os

from horovod_tpu.analysis import lint_paths, unsuppressed

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LINT_PATHS = [os.path.join(_REPO, "horovod_tpu"),
               os.path.join(_REPO, "examples")]


def test_repo_is_hvdlint_clean():
    findings = lint_paths(_LINT_PATHS)
    active = unsuppressed(findings)
    assert not active, (
        "hvdlint found new distributed-correctness antipatterns — fix "
        "them or suppress each with a reasoned '# hvdlint: disable=...' "
        "comment:\n" + "\n".join(f.format() for f in active))


def test_lint_covers_the_whole_tree():
    """Guard the gate itself: if path walking ever silently breaks (e.g.
    an overzealous skip list), this fails before a regression can hide."""
    from horovod_tpu.analysis import iter_python_files
    files = iter_python_files(_LINT_PATHS)
    # The seed tree has ~90 framework files + 8 examples; a collapse of
    # the walker to a handful of files must trip this.
    assert len(files) > 50
    assert any(f.endswith("optimizer.py") for f in files)
    assert any(f.endswith("mnist_mlp.py") for f in files)
    # The serve/ subsystem (ISSUE 4) must stay inside the gate's walk —
    # a skip-list regression here would let serving-path antipatterns
    # land unlinted.
    serve_files = [f for f in files
                   if os.sep + os.path.join("serve", "") in f]
    # sampling.py (ISSUE 11) carries the serving PRNG discipline the new
    # HVD010 rule audits — it must stay inside the gate's walk.
    # controller.py (ISSUE 13) holds the fleet control plane — the
    # autoscale/brownout decision loop must stay under the same lint.
    # tenancy.py / registry.py (ISSUE 15) carry the fairness scheduler
    # and the hot-swap walk — same deal.
    # router.py / router_server.py (ISSUE 18) carry the front-door
    # retry/hedge/health machinery — same deal.
    # seqpar.py (ISSUE 20) carries the sequence-parallel prefill world
    # — the rank-block/handoff machinery must stay under the same lint.
    for mod in ("engine.py", "batcher.py", "blocks.py", "replica.py",
                "server.py", "metrics.py", "paged_attention.py",
                "sampling.py", "controller.py", "tenancy.py",
                "registry.py", "tiering.py", "router.py",
                "router_server.py", "seqpar.py"):
        assert any(f.endswith(os.path.join("serve", mod))
                   for f in serve_files), f"serve/{mod} not linted"
    # Same for faultline/ (ISSUE 6): the injection layer must stay under
    # the swallowed-fault rule it motivated (HVD009).
    for mod in ("plan.py", "runtime.py"):
        assert any(f.endswith(os.path.join("faultline", mod))
                   for f in files), f"faultline/{mod} not linted"
    # And obs/ (ISSUE 9): the tracing plane threads through the serve
    # hot paths and the KV client — it must stay inside the gate.
    for mod in ("tracing.py", "merge.py", "cli.py"):
        assert any(f.endswith(os.path.join("obs", mod))
                   for f in files), f"obs/{mod} not linted"
    # And the hvdmem analyzer itself (ISSUE 10): memplan.py must pass
    # the lint the rest of the repo is held to.
    assert any(f.endswith(os.path.join("analysis", "memplan.py"))
               for f in files), "analysis/memplan.py not linted"
    # And the hvdshard analyzer (ISSUE 17): shardplan.py must pass the
    # same lint — including the HVD011 sync-under-lock rule it shipped
    # beside.
    assert any(f.endswith(os.path.join("analysis", "shardplan.py"))
               for f in files), "analysis/shardplan.py not linted"
    assert not any("__pycache__" in f for f in files)


def test_suppressions_are_auditable():
    """Every suppressed finding in the repo still surfaces with
    suppressed=True — the audit trail the dogfooding satellite requires."""
    findings = lint_paths(_LINT_PATHS)
    for f in findings:
        assert f.suppressed, f.format()
