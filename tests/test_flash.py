"""Pallas flash attention: exactness vs dense reference (CPU interpret mode)
and integration with Ulysses sequence parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel.flash import flash_attention
from horovod_tpu.parallel.ring import ring_attention_reference
from horovod_tpu.parallel.ulysses import ulysses_attention

B, S, H, D = 2, 128, 4, 32


def _qkv(seed):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(hvd8, causal):
    q, k, v = _qkv(0)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    expected = ring_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_flash_uneven_block_sizes(hvd8):
    q, k, v = _qkv(1)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=32)
    expected = ring_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_flash_indivisible_seq_rejected(hvd8):
    q = jnp.ones((1, 100, 2, 16))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, q, q, block_q=64, block_k=64)


def test_flash_bf16(hvd8):
    q, k, v = _qkv(2)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = flash_attention(qb, kb, vb, causal=False, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    expected = ring_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(expected, np.float32),
                               rtol=0.1, atol=0.05)


def test_flash_inside_ulysses(hvd8):
    """Ulysses with the Pallas kernel as the local attention backend."""
    rng = np.random.RandomState(3)
    mk = lambda: jnp.asarray(rng.randn(2, 64, 8, 32).astype(np.float32) * 0.3)
    q, k, v = mk(), mk(), mk()
    mesh = hvd8.mesh()

    def body(a, b, c):
        return ulysses_attention(
            a, b, c, causal=True,
            attention_fn=lambda *t, **kw: flash_attention(
                *t, block_q=32, block_k=32, **kw))

    # check_vma=False: the Pallas *interpreter* inlines the kernel into the
    # jaxpr where loop indices (invariant) mix with data (varying); the real
    # TPU lowering is a single opaque primitive and needs no escape hatch.
    out = jax.jit(jax.shard_map(body, mesh=mesh,
                                in_specs=(P(None, "hvd"),) * 3,
                                out_specs=P(None, "hvd"),
                                check_vma=False))(q, k, v)
    expected = ring_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_transformer_flash_impl_matches_dense(hvd8):
    import dataclasses
    from horovod_tpu.models import Transformer, TransformerConfig
    cfg = TransformerConfig(vocab_size=64, num_layers=1, num_heads=2,
                            d_model=32, d_ff=64, max_len=64, causal=True,
                            dtype=jnp.float32)
    cfg_f = dataclasses.replace(cfg, attention_impl="flash")
    toks = jnp.asarray(np.random.RandomState(4).randint(0, 64, (2, 64)))
    params = Transformer(cfg).init(jax.random.PRNGKey(0), toks)
    a = Transformer(cfg).apply(params, toks)
    b = Transformer(cfg_f).apply(params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(hvd8, causal):
    """custom_vjp backward kernels vs autodiff through the dense reference."""
    q, k, v = _qkv(5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * jnp.cos(o))

    def loss_dense(q, k, v):
        o = ring_attention_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4, err_msg=name)


def test_flash_gradients_uneven_blocks(hvd8):
    q, k, v = _qkv(6)
    f = lambda *t: jnp.sum(flash_attention(*t, causal=True, block_q=64,
                                           block_k=32) ** 2)
    d = lambda *t: jnp.sum(ring_attention_reference(*t, causal=True) ** 2)
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_transformer_flash_training_step(hvd8):
    """attention_impl='flash' must train (grad through the Pallas VJP)."""
    import dataclasses
    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.models.transformer import lm_loss
    cfg = TransformerConfig(vocab_size=64, num_layers=1, num_heads=2,
                            d_model=32, d_ff=64, max_len=64, causal=False,
                            dtype=jnp.float32, attention_impl="flash")
    toks = jnp.asarray(np.random.RandomState(7).randint(0, 64, (2, 64)))
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), toks)
    g = jax.grad(lambda p: lm_loss(model.apply(p, toks), toks))(params)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(x)).all() for x in flat)
    assert any(float(jnp.max(jnp.abs(x))) > 0 for x in flat)


def test_flash_supported_probe(monkeypatch):
    """auto attention selection must degrade to dense when the kernels
    don't compile on the claimed backend — here the probe really attempts
    a TPU lowering on a box with no TPU compiler, which is exactly the
    Mosaic-rejection shape the fallback exists for."""
    from horovod_tpu.parallel import flash as F
    try:
        F.flash_supported.cache_clear()
        # CPU: interpret path always works
        assert F.flash_supported() is True
        F.flash_supported.cache_clear()
        monkeypatch.setattr(F.jax, "default_backend", lambda: "tpu")
        # compile fails -> dense fallback
        assert F.flash_supported() is False
    finally:
        # Never leave a verdict computed under the faked backend cached.
        F.flash_supported.cache_clear()
