"""hvdshard — static sharding & communication-plan analysis (HVD4xx).

Acceptance coverage (ISSUE 17):

* COMM_CENSUS bytes on a hand-built 2-axis program equal HAND-COMPUTED
  bytes exactly (payload x communicator group size, per-axis
  attribution, ICI/DCN split);
* a seeded corpus fires each of HVD400-HVD404 exactly where expected —
  jaxpr-level (implicit reshard with estimated bytes, budget overshoot,
  replicated-large operand, undeclared/mixed-fabric collective, dead
  mesh axis) and AST-level (pinned lines) — with clean-fixture
  negatives: deliberate resharding via an explicit constraint, an
  ICI-only program under a DCN budget, scan-carried shardings
  unchanged;
* ``check_replica_plan()`` rejects a plan whose per-step DCN bytes
  exceed the budget and admits the ICI-only equivalent; the serve
  engine exposes the verdict on ``kv_stats`` (→ healthz);
* COMM_CENSUS counters land on the Timeline and the HVD_ANALYZE hook
  attaches ``comm`` to shard_step reports on the SAME trace the
  collective/memory censuses use.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu import core as _core
from horovod_tpu.analysis import hook, shardplan, unsuppressed

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

F32 = 4  # bytes


def _mesh(shape, names):
    n = int(np.prod(shape))
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)


@pytest.fixture()
def analyze_env(monkeypatch):
    monkeypatch.setenv("HVD_ANALYZE", "1")
    hook.reset()
    _core._state.analysis_reports = []
    yield
    hook.reset()


# ---------------------------------------------------------------------------
# Census: hand-computed bytes
# ---------------------------------------------------------------------------

def test_census_bytes_two_axis_hand_computed():
    """Hand-built 2-axis program: psum of 64 payload bytes over 'local'
    (group 4) = 256 wire bytes; psum of 32 payload bytes over both axes
    (group 8) = 256 wire bytes.  Totals and the per-axis attribution
    (every collective that names an axis charges it) must match these
    numbers EXACTLY."""
    def step(x, y):
        return jax.lax.psum(x, "local"), jax.lax.psum(y, ("cross", "local"))

    r = shardplan.measure_step_fn_comm(
        step, (jnp.ones((16,), jnp.float32), jnp.ones((8,), jnp.float32)),
        axis_env=[("cross", 2), ("local", 4)], label="two_axis")
    assert r.by_primitive["psum"]["count"] == 2
    assert r.by_primitive["psum"]["bytes"] == 16 * F32 + 8 * F32
    assert r.by_primitive["psum"]["wire_bytes"] == 256 + 256
    assert r.total_wire_bytes == 512
    assert r.dcn_wire_bytes == 0
    assert r.by_axis["local"] == {"fabric": "ici", "size": 4,
                                  "count": 2, "wire_bytes": 512}
    assert r.by_axis["cross"] == {"fabric": "ici", "size": 2,
                                  "count": 1, "wire_bytes": 256}
    assert not r.findings


def test_shard_map_census_group_size():
    """Through the repo's shard_map wrapper (compat shim): the per-shard
    psum payload is (1, 128) f32 = 512 bytes, wire = 512 x group 8."""
    mesh = _mesh((8,), ("hvd",))

    def step(x):
        return jax.lax.psum(x, "hvd")

    mapped = jax.shard_map(step, mesh=mesh, in_specs=P("hvd"),
                           out_specs=P("hvd"))
    closed = jax.make_jaxpr(mapped)(jnp.zeros((8, 128), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="sm", mesh=mesh)
    assert r.by_primitive["psum"] == {"count": 1, "bytes": 512,
                                      "wire_bytes": 4096, "dcn_bytes": 0}
    assert r.axes_declared == {"hvd": 8}
    assert not r.findings


def test_rewrite_mode_psum2_counts_as_psum():
    """shard_map's rewrite mode (check_rep=True) spells psum as the
    psum2 primitive — the census must normalize it so a modern-jax
    trace measures identically to the compat-shim trace."""
    from jax.experimental.shard_map import shard_map as raw_sm
    mesh = _mesh((8,), ("hvd",))

    def step(x):
        return jax.lax.psum(x, "hvd")

    mapped = raw_sm(step, mesh=mesh, in_specs=P("hvd"),
                    out_specs=P("hvd"), check_rep=True)
    closed = jax.make_jaxpr(mapped)(jnp.zeros((8, 128), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="sm2", mesh=mesh)
    assert "psum2" not in r.by_primitive
    assert r.by_primitive["psum"]["count"] == 1
    assert r.by_primitive["psum"]["wire_bytes"] == 4096


def test_scan_census_multiplied_and_carried_sharding_clean():
    """A psum inside a length-5 scan executes 5 times (unlike the
    MEMORY census, wire bytes DO multiply by trip count); the scan
    carry's sharding never changes, so no HVD400."""
    def step(x):
        def body(c, _):
            return jax.lax.psum(c, "hvd"), ()
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    r = shardplan.measure_step_fn_comm(
        step, (jnp.ones((16,), jnp.float32),),
        axis_env=[("hvd", 8)], label="scan")
    assert r.by_primitive["psum"]["count"] == 5
    assert r.by_primitive["psum"]["wire_bytes"] == 5 * (16 * F32) * 8
    assert not [f for f in r.findings if f.rule == "HVD400"]


# ---------------------------------------------------------------------------
# ICI/DCN classification
# ---------------------------------------------------------------------------

def test_classify_mesh_axes_single_host_ici_and_override():
    """Single-process CPU mesh: every axis is ICI (process_index never
    changes along any dim); HVD_COMM_DCN_AXES-style override forces the
    listed axis to DCN."""
    mesh = _mesh((2, 4), ("cross", "local"))
    assert shardplan.classify_mesh_axes(mesh) == \
        {"cross": "ici", "local": "ici"}
    assert shardplan.classify_mesh_axes(mesh, dcn_axes=("cross",)) == \
        {"cross": "dcn", "local": "ici"}


# ---------------------------------------------------------------------------
# HVD400: implicit resharding (jaxpr)
# ---------------------------------------------------------------------------

def _row_col(mesh):
    return (NamedSharding(mesh, P("hvd", None)),
            NamedSharding(mesh, P(None, "hvd")))


def test_implicit_reshard_fires_with_estimated_bytes():
    """Produced row-sharded, consumed column-sharded: HVD400 with the
    full array size as the transfer estimate (512x512 f32 = 1 MiB)."""
    mesh = _mesh((8,), ("hvd",))
    row, col = _row_col(mesh)
    inner1 = jax.jit(lambda x: x * 2.0, in_shardings=(row,),
                     out_shardings=row)
    inner2 = jax.jit(lambda x: x + 1.0, in_shardings=(col,),
                     out_shardings=col)
    closed = jax.make_jaxpr(lambda x: inner2(inner1(x)))(
        jnp.zeros((512, 512), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="reshard",
                                            mesh=mesh)
    fired = [f for f in r.findings if f.rule == "HVD400"]
    assert len(fired) == 1, [f.format() for f in r.findings]
    assert r.reshard_bytes == 512 * 512 * F32
    assert r.total_wire_bytes == 512 * 512 * F32
    (ev,) = r.reshard_events
    assert ev["from"] == "P(hvd, None)"
    assert ev["to"] == "P(None, hvd)"
    assert ev["bytes"] == 512 * 512 * F32


def test_explicit_constraint_resharding_is_clean():
    """The SAME layout change via with_sharding_constraint is the
    deliberate-resharding idiom: the constraint updates the value's
    sharding and the downstream consumption matches — no HVD400."""
    mesh = _mesh((8,), ("hvd",))
    row, col = _row_col(mesh)
    inner1 = jax.jit(lambda x: x * 2.0, in_shardings=(row,),
                     out_shardings=row)
    inner2 = jax.jit(lambda x: x + 1.0, in_shardings=(col,),
                     out_shardings=col)

    def prog(x):
        y = inner1(x)
        y = jax.lax.with_sharding_constraint(y, col)
        return inner2(y)

    closed = jax.make_jaxpr(prog)(jnp.zeros((512, 512), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="deliberate",
                                            mesh=mesh)
    assert not [f for f in r.findings if f.rule == "HVD400"], \
        [f.format() for f in r.findings]
    assert r.reshard_bytes == 0


def test_reshard_below_floor_is_noise_not_finding():
    """A re-laid-out 16 KiB value is under RESHARD_MIN_BYTES: counted
    nowhere, flagged nowhere."""
    mesh = _mesh((8,), ("hvd",))
    row, col = _row_col(mesh)
    inner1 = jax.jit(lambda x: x * 2.0, in_shardings=(row,),
                     out_shardings=row)
    inner2 = jax.jit(lambda x: x + 1.0, in_shardings=(col,),
                     out_shardings=col)
    closed = jax.make_jaxpr(lambda x: inner2(inner1(x)))(
        jnp.zeros((64, 64), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="small",
                                            mesh=mesh)
    assert not r.findings
    assert r.reshard_bytes == 0


# ---------------------------------------------------------------------------
# HVD401: comm budget (and the DCN sub-budget)
# ---------------------------------------------------------------------------

def test_comm_budget_overshoot_fires():
    def step(x):
        return jax.lax.psum(x, "hvd")

    r = shardplan.measure_step_fn_comm(
        step, (jnp.ones((128,), jnp.float32),),
        axis_env=[("hvd", 8)], budget_bytes=1000, label="budget")
    # wire = 512 payload x group 8 = 4096 > 1000
    fired = [f for f in r.findings if f.rule == "HVD401"]
    assert len(fired) == 1
    assert r.headroom_bytes == 1000 - 4096


def test_dcn_sub_budget_fires_only_for_dcn_bytes():
    """The same program under the same DCN sub-budget: over budget when
    its axis is DCN, clean when ICI-only (dcn_wire_bytes stays 0) —
    the ISSUE's ICI-only-under-DCN-budget negative."""
    def step(x):
        return jax.lax.psum(x, "hvd")

    args = (jnp.ones((128,), jnp.float32),)
    dcn = shardplan.measure_step_fn_comm(
        step, args, axis_env=[("hvd", 8)], dcn_axes=("hvd",),
        dcn_budget=1000, label="dcn_heavy")
    assert dcn.dcn_wire_bytes == 4096
    fired = [f for f in dcn.findings if f.rule == "HVD401"]
    assert len(fired) == 1 and "DCN" in fired[0].message

    ici = shardplan.measure_step_fn_comm(
        step, args, axis_env=[("hvd", 8)], dcn_axes=(),
        dcn_budget=1000, label="ici_only")
    assert ici.dcn_wire_bytes == 0
    assert not [f for f in ici.findings if f.rule == "HVD401"]


def test_budget_env_knobs(monkeypatch):
    monkeypatch.setenv("HVD_COMM_BUDGET_BYTES", "123")
    assert shardplan.comm_budget_bytes() == 123
    monkeypatch.setenv("HVD_COMM_BUDGET_BYTES", "not-a-number")
    assert shardplan.comm_budget_bytes() is None
    monkeypatch.setenv("HVD_COMM_DCN_BUDGET_BYTES", "77")
    assert shardplan.dcn_budget_bytes() == 77
    monkeypatch.setenv("HVD_COMM_DCN_AXES", "cross, pp")
    assert shardplan.dcn_axes_override() == ("cross", "pp")


# ---------------------------------------------------------------------------
# HVD402: replicated-large operand
# ---------------------------------------------------------------------------

def test_replicated_large_operand_fires():
    """A 1 MiB fully-replicated operand next to an 'hvd'-sharded peer,
    with 8 | 512: sharding it would save 7/8 of the copy per device."""
    mesh = _mesh((8,), ("hvd",))
    row = NamedSharding(mesh, P("hvd", None))
    rep = NamedSharding(mesh, P(None, None))
    inner = jax.jit(lambda x, w: x @ w, in_shardings=(row, rep),
                    out_shardings=row)
    closed = jax.make_jaxpr(inner)(
        jnp.zeros((512, 512), jnp.float32),
        jnp.zeros((512, 512), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="replicated",
                                            mesh=mesh)
    fired = [f for f in r.findings if f.rule == "HVD402"]
    assert len(fired) == 1, [f.format() for f in r.findings]
    assert "'hvd'" in fired[0].message


def test_replicated_small_bias_is_clean():
    """The normal data-parallel layout — a replicated 2 KiB bias next to
    a sharded batch — is NOT a finding (under REPLICATED_MIN_BYTES)."""
    mesh = _mesh((8,), ("hvd",))
    row = NamedSharding(mesh, P("hvd", None))
    rep = NamedSharding(mesh, P(None))
    inner = jax.jit(lambda x, b: x + b, in_shardings=(row, rep),
                    out_shardings=row)
    closed = jax.make_jaxpr(inner)(
        jnp.zeros((512, 512), jnp.float32),
        jnp.zeros((512,), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="bias",
                                            mesh=mesh)
    assert not [f for f in r.findings if f.rule == "HVD402"]


# ---------------------------------------------------------------------------
# HVD403: undeclared axis / mixed process-set scopes
# ---------------------------------------------------------------------------

def test_undeclared_axis_collective_fires():
    """The deployment mesh declares only 'hvd'; a collective over
    'rogue' names a process set that does not exist there."""
    def step(x):
        return jax.lax.psum(x, "rogue")

    closed = jax.make_jaxpr(step, axis_env=[("rogue", 2)])(
        jnp.ones((4,), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(closed, label="rogue",
                                            axis_sizes={"hvd": 8})
    fired = [f for f in r.findings if f.rule == "HVD403"]
    assert len(fired) == 1 and "'rogue'" in fired[0].message


def test_mixed_ici_dcn_flat_collective_fires():
    """One flat psum spanning an ICI axis and a DCN axis moves the whole
    payload at DCN speed — flagged; the wire bytes count as DCN."""
    def step(x):
        return jax.lax.psum(x, ("cross", "local"))

    r = shardplan.measure_step_fn_comm(
        step, (jnp.ones((8,), jnp.float32),),
        axis_env=[("cross", 2), ("local", 4)], dcn_axes=("cross",),
        label="mixed")
    fired = [f for f in r.findings if f.rule == "HVD403"]
    assert len(fired) == 1 and "hierarchically" in fired[0].message
    assert r.dcn_wire_bytes == r.total_wire_bytes == 8 * F32 * 8


# ---------------------------------------------------------------------------
# HVD404: dead mesh axes (jaxpr)
# ---------------------------------------------------------------------------

def test_dead_mesh_axis_fires_size_one_exempt():
    """'dead' (size 4) is never named by a collective or a spec → HVD404;
    a size-1 axis is free and never flagged."""
    def step(x):
        return jax.lax.psum(x, "hvd")

    closed = jax.make_jaxpr(step, axis_env=[("hvd", 8)])(
        jnp.ones((4,), jnp.float32))
    r = shardplan.measure_closed_jaxpr_comm(
        closed, label="dead",
        axis_sizes={"hvd": 8, "dead": 4, "solo": 1})
    fired = [f for f in r.findings if f.rule == "HVD404"]
    assert len(fired) == 1 and "'dead'" in fired[0].message
    assert r.axes_used == {"hvd"}


# ---------------------------------------------------------------------------
# check_replica_plan: the serve-layer go/no-go
# ---------------------------------------------------------------------------

def test_replica_plan_rejects_dcn_over_budget_admits_ici_equivalent():
    """The acceptance pair: identical plans except where the bytes flow —
    the DCN-heavy one is rejected (HVD401), the ICI-only one admitted."""
    bad = shardplan.check_replica_plan(
        "plan:dcn", step_comm_bytes=1 << 20, step_dcn_bytes=1 << 20,
        comm_budget=1 << 22, dcn_budget=1 << 16)
    assert bad.go is False
    assert [f.rule for f in bad.findings] == ["HVD401"]
    assert bad.comm["dcn_headroom_bytes"] == (1 << 16) - (1 << 20)

    good = shardplan.check_replica_plan(
        "plan:ici", step_comm_bytes=1 << 20, step_dcn_bytes=0,
        comm_budget=1 << 22, dcn_budget=1 << 16)
    assert good.go is True and not good.findings
    assert good.comm["headroom_bytes"] == (1 << 22) - (1 << 20)


def test_replica_plan_folds_mem_verdict():
    """A pool past the memory budget fails the plan through hvdmem's
    HVD302 — one combined verdict, not two surfaces to check."""
    bad = shardplan.check_replica_plan(
        "plan:mem", pool_bytes=2 << 20, weight_bytes=0,
        mem_budget_bytes=1 << 20)
    assert bad.go is False
    assert [f.rule for f in bad.findings] == ["HVD302"]
    assert bad.mem["headroom_bytes"] < 0


def _small_engine(**kw):
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.serve import (InferenceEngine, ServeMetrics,
                                   TransformerAdapter)
    cfg = TransformerConfig(vocab_size=64, causal=True,
                            dtype=jnp.float32, scan_layers=False,
                            num_layers=2, num_heads=2, d_model=32,
                            d_ff=64, max_len=32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    adapter = TransformerAdapter(cfg, params, block_tokens=8)
    engine = InferenceEngine(adapter, max_batch=2, kv_mode="paged",
                             metrics=ServeMetrics(),
                             replica_id="shardplan-test", **kw)
    return adapter, engine


def test_engine_exposes_plan_go_on_kv_stats(monkeypatch):
    """A data-parallel replica (zero step comm bytes) passes trivially;
    the verdict rides kv_stats → replica healthz."""
    monkeypatch.setenv("HVD_MEM_BUDGET_BYTES", str(1 << 30))
    _core._state.analysis_reports = []
    _, engine = _small_engine()
    stats = engine.kv_stats()
    assert stats["plan_go"] is True
    assert stats["plan_findings"] == 0


def test_engine_plan_rejects_dcn_heavy_adapter(monkeypatch):
    """An adapter declaring per-step DCN bytes past the sub-budget is
    flagged at CONSTRUCTION (no traffic needed): plan_go False on
    kv_stats, the verdict published to analysis_reports."""
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.serve import (InferenceEngine, ServeMetrics,
                                   TransformerAdapter)
    monkeypatch.setenv("HVD_MEM_BUDGET_BYTES", str(1 << 30))
    monkeypatch.setenv("HVD_COMM_DCN_BUDGET_BYTES", "1024")
    _core._state.analysis_reports = []
    cfg = TransformerConfig(vocab_size=64, causal=True,
                            dtype=jnp.float32, scan_layers=False,
                            num_layers=2, num_heads=2, d_model=32,
                            d_ff=64, max_len=32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    adapter = TransformerAdapter(cfg, params, block_tokens=8)
    adapter.step_comm_bytes = 1 << 20
    adapter.step_dcn_bytes = 1 << 20
    engine = InferenceEngine(adapter, max_batch=2, kv_mode="paged",
                             metrics=ServeMetrics(),
                             replica_id="shardplan-dcn")
    stats = engine.kv_stats()
    assert stats["plan_go"] is False
    assert stats["plan_findings"] >= 1
    published = [r for r in _core.analysis_reports()
                 if getattr(r, "label", "").endswith(":plan")]
    assert published and published[-1].go is False


# ---------------------------------------------------------------------------
# Surfacing: Timeline counters + the HVD_ANALYZE hook ride-along
# ---------------------------------------------------------------------------

def test_comm_census_lands_on_timeline(tmp_path):
    """COMM_CENSUS counter events mirror MEMORY_CENSUS: one totals
    counter, one per collective primitive, one per axis tagged with its
    fabric."""
    from horovod_tpu.timeline import Timeline

    def step(x):
        return jax.lax.psum(x, "hvd")

    r = shardplan.measure_step_fn_comm(
        step, (jnp.ones((128,), jnp.float32),),
        axis_env=[("hvd", 8)], label="comm_step")
    path = str(tmp_path / "comm_timeline.json")
    tl = Timeline(path, rank=0)
    tl.comm_census("comm_step", r.to_dict())
    tl.close()
    with open(path) as fh:
        events = json.load(fh)
    names = [e.get("name", "") for e in events]
    assert "COMM_CENSUS/comm_step" in names
    assert "COMM_CENSUS/comm_step/psum" in names
    assert "COMM_CENSUS/comm_step/axis/hvd[ici]" in names
    totals = next(e for e in events
                  if e.get("name") == "COMM_CENSUS/comm_step")
    assert totals["ph"] == "C"
    assert totals["args"]["total_wire_bytes"] == r.total_wire_bytes == 4096


def test_hook_attaches_comm_to_training_reports(analyze_env, hvd8):
    """The HVD_ANALYZE hook runs the sharding walk on the SAME trace as
    the collective + memory censuses — a shard_step report carries all
    three, and the mesh seeds the declared axes."""
    import horovod_tpu as hvd

    def local_step(x):
        return jax.lax.psum(x * 2.0, "hvd")

    step = hvd.shard_step(local_step, in_specs=(P("hvd"),),
                          out_specs=P("hvd"))
    step(jnp.ones((8, 4), jnp.float32))
    reports = [r for r in _core.analysis_reports()
               if getattr(r, "comm", None)]
    assert reports, "no report carried a comm census"
    comm = reports[-1].comm
    assert comm["by_primitive"]["psum"]["count"] >= 1
    assert comm["axes_declared"] == {"hvd": 8}
    assert comm["by_axis"]["hvd"]["fabric"] == "ici"


# ---------------------------------------------------------------------------
# AST corpus: HVD400/HVD404 source shapes at pinned lines
# ---------------------------------------------------------------------------

SRC_HVD400 = """\
import jax
from jax.sharding import PartitionSpec as P

def step(x, w):
    a = jax.lax.with_sharding_constraint(x, P("dp"))
    b = jax.lax.with_sharding_constraint(x, P(None, "tp"))
    return a + b + w
"""

SRC_HVD400_REBIND_CLEAN = """\
import jax
from jax.sharding import PartitionSpec as P

def step(x):
    y = jax.lax.with_sharding_constraint(x, P("dp"))
    z = jax.lax.with_sharding_constraint(y, P(None, "tp"))
    return z
"""

SRC_HVD404 = """\
from jax.sharding import Mesh, PartitionSpec as P

def layout(devs):
    mesh = Mesh(devs, ("dp", "tp"))
    spec = P("dp")
    return spec
"""

SRC_HVD404_ESCAPED_CLEAN = """\
from jax.sharding import Mesh, PartitionSpec as P

def layout(devs):
    mesh = Mesh(devs, ("dp", "tp"))
    spec = P("dp")
    return mesh
"""


def _rules_lines(findings):
    return [(f.rule, f.line) for f in unsuppressed(findings)]


def test_ast_hvd400_second_annotation_pinned_line():
    fs = shardplan.analyze_source(SRC_HVD400, "corpus.py")
    assert _rules_lines(fs) == [("HVD400", 6)]
    assert "'x'" in fs[0].message


def test_ast_hvd400_rebinding_is_the_clean_idiom():
    assert shardplan.analyze_source(SRC_HVD400_REBIND_CLEAN,
                                    "clean.py") == []


def test_ast_hvd404_dead_axis_pinned_at_mesh_ctor():
    fs = shardplan.analyze_source(SRC_HVD404, "corpus.py")
    assert _rules_lines(fs) == [("HVD404", 4)]
    assert "'tp'" in fs[0].message


def test_ast_hvd404_escaped_mesh_is_clean():
    """A returned mesh's axes may be exercised by callers — skipped."""
    assert shardplan.analyze_source(SRC_HVD404_ESCAPED_CLEAN,
                                    "clean.py") == []


def test_ast_pragma_suppression_retained_for_audit():
    src = SRC_HVD400.replace(
        '    b = jax.lax.with_sharding_constraint(x, P(None, "tp"))',
        '    b = jax.lax.with_sharding_constraint(x, P(None, "tp"))'
        '  # hvdlint: disable=HVD400')
    fs = shardplan.analyze_source(src, "sup.py")
    assert len(fs) == 1 and fs[0].suppressed
    assert unsuppressed(fs) == []


def test_ast_select_ignore_prefix_contract():
    assert shardplan.analyze_source(SRC_HVD400, "s.py",
                                    select=["HVD4"])
    assert shardplan.analyze_source(SRC_HVD400, "s.py",
                                    select=["HVD404"]) == []
    assert shardplan.analyze_source(SRC_HVD400, "s.py",
                                    ignore=["HVD4"]) == []


def test_ast_parse_failure_is_a_finding_not_a_crash():
    fs = shardplan.analyze_source("def broken(:\n", "bad.py")
    assert [f.rule for f in fs] == ["HVD000"]
