"""Seeded sampling, speculative decoding, and CoW-forked n-best
(ISSUE 11): the serve engine's decode-algorithm layer.

Pins the revised exactness contract — **batched == single given the same
key** — and the four decode-algorithm properties the tentpole is judged
on:

* per-request seeded sampling is bit-reproducible at any batch
  composition, block-boundary prompt length, and replay;
* an n>1 request prefills its prompt ONCE and forks through the
  BlockManager's copy-on-write tables (shared prompt blocks counted
  once, fork count == n-1, zero leaked refs at completion);
* greedy speculative decoding is bit-identical to non-speculative
  greedy (and rolls rejected-draft block state back without leaks);
* sampled speculative decoding matches the target filtered distribution
  statistically (chi-square on a tiny vocab) — the Leviathan/Chen
  rejection-sampling guarantee.

HTTP-surface validation (per-field 400s, seed echo, n-best completions,
fork counters on /metrics + healthz) rides the same file.
"""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import create_mlp
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.serve import (InferenceEngine, MLPAdapter, Replica,
                               ReplicaScheduler, Request, ServeMetrics,
                               ServeServer, TransformerAdapter)
from horovod_tpu.serve import sampling

BT = 8  # block_tokens used throughout (small, so boundaries are cheap)

_TINY = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_len=64, causal=True,
                          dtype=jnp.float32, scan_layers=False)


def _tiny(seed=0):
    model = Transformer(_TINY)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


_SHARED = {}


def _shared_adapter():
    """One draft-capable adapter shared by every default-params engine
    in this file: the per-bucket compile caches live on the adapter, so
    sharing it keeps the file's transformer compile cost to one set
    (a draft_layers=1 adapter serves plain greedy identically — the
    draft programs only run when an engine enables spec_k)."""
    if "ad" not in _SHARED:
        _, params = _tiny()
        _SHARED["params"] = params
        _SHARED["ad"] = TransformerAdapter(_TINY, params, block_tokens=BT,
                                           draft_layers=1)
    return _SHARED["ad"]


def _mlp_adapter(seed=3, vocab=13, max_len=128):
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return MLPAdapter(mlp, params, vocab_size=vocab, max_len=max_len)


def _engine(params=None, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_chunk", 5)  # deliberately unaligned with BT
    kw.setdefault("metrics", ServeMetrics())
    draft = kw.pop("draft_layers", None)
    ad = kw.pop("adapter", None)
    if ad is None:
        ad = (TransformerAdapter(_TINY, params, block_tokens=BT,
                                 draft_layers=draft)
              if params is not None else _shared_adapter())
    kw.setdefault("replica_id", "sampling-t")
    return InferenceEngine(ad, kv_mode="paged", **kw)


# -- validation (the /generate payload contract) -----------------------------

def test_validate_params_per_field_rejections():
    ok = sampling.validate_params(0.7, 5, 0.9, 2, 11)
    assert ok == (0.7, 5, 0.9, 2, 11)
    for bad in [(-0.1, None, 1.0, 1, None),       # temperature < 0
                (float("nan"), None, 1.0, 1, None),
                (0.5, 0, 1.0, 1, None),           # top_k < 1
                (0.5, -3, 1.0, 1, None),
                (0.5, 2.5, 1.0, 1, None),         # non-int top_k
                (0.5, None, 0.0, 1, None),        # top_p out of (0, 1]
                (0.5, None, 1.5, 1, None),
                (0.5, None, 1.0, 0, None),        # n < 1
                (0.5, None, 1.0, 1.5, None),      # non-int n
                (0.5, None, 1.0, 1, "abc"),       # non-int seed
                (0.5, None, 1.0, 1, 1.5),
                (0.5, None, 1.0, 1, True),        # bool is not a seed
                (True, None, 1.0, 1, None),       # ...nor a temperature
                (0.5, True, 1.0, 1, None),        # ...nor a top_k
                (0.5, None, True, 1, None),       # ...nor a top_p
                (0.5, None, 1.0, True, None)]:    # ...nor an n
        with pytest.raises((ValueError, TypeError)):
            sampling.validate_params(*bad)
    # A missing seed is ASSIGNED (the reproducibility handle is always
    # echoed), greedy stays the default.
    t, k, p, n, seed = sampling.validate_params(0.0, None, 1.0, 1, None)
    assert (t, k, p, n) == (0.0, None, 1.0, 1)
    assert isinstance(seed, int) and seed >= 0
    r = Request([1, 2], temperature=0.0)
    assert not r.sampled and isinstance(r.seed, int)
    assert r.samples is None  # n == 1 keeps the legacy surface
    assert Request([1, 2], temperature=0.3, n=2).samples == [None, None]


def test_filtered_probs_host_matches_traced_filter():
    """The host filter (speculative accept/resample reference) and the
    in-jit filter (sampled decode programs) must describe the SAME
    distribution — support and probabilities."""
    rng = np.random.RandomState(0)
    for temp, tk, tp in [(0.7, None, 1.0), (1.3, 4, 1.0),
                         (0.9, None, 0.6), (1.0, 5, 0.8)]:
        logits = rng.randn(17).astype(np.float32) * 2
        host = sampling.filtered_probs(logits, temp, tk, tp)
        traced = np.asarray(jax.nn.softmax(
            sampling._filter_logits_jnp(jnp.asarray(logits),
                                        jnp.float32(temp),
                                        jnp.int32(tk or 0),
                                        jnp.float32(tp))))
        assert (host > 0).tolist() == (traced > 1e-9).tolist()
        np.testing.assert_allclose(host, traced, atol=1e-5)


def test_spec_accept_resample_preserves_target_distribution():
    """Leviathan rejection with a point-mass (greedy) draft: accept the
    draft d with probability p[d], else draw the residual — the marginal
    must be exactly the filtered target distribution p.  Chi-square on a
    tiny vocab over many positions (deterministic: fixed seed keys)."""
    rng = np.random.RandomState(7)
    logits = rng.randn(6).astype(np.float32) * 1.5
    temp, tk, tp = 1.1, None, 0.95
    p = sampling.filtered_probs(logits, temp, tk, tp)
    d = int(np.argmax(logits))  # the greedy draft's proposal
    key = sampling.seq_key(1234, 0)
    N = 4000
    counts = np.zeros(len(p))
    for pos in range(N):
        if sampling.accept_draw(key, pos) < p[d]:
            counts[d] += 1
        else:
            counts[sampling.residual_sample(p, d, key, pos)] += 1
    expected = p * N
    live = expected > 0
    chi2 = float(((counts[live] - expected[live]) ** 2
                  / expected[live]).sum())
    # df <= 5; the 99.9th percentile of chi2(5) is 20.5 — a generous,
    # deterministic bound (fixed keys: this either always passes or
    # always fails).
    assert chi2 < 20.5, (chi2, counts, expected)
    assert counts[~live].sum() == 0  # nothing outside the support


# -- batched == single given the same key ------------------------------------

def test_batched_equals_single_given_same_key_at_block_boundaries():
    """Sampled requests at k*BT-1 / k*BT / k*BT+1 prompt lengths, mixed
    params, one greedy row riding along: the batched storm must emit
    bit-identical streams to each request run ALONE with the same seed
    (and the greedy row must match a greedy-only engine)."""
    rng = np.random.RandomState(1)
    rows = [
        (rng.randint(0, 61, size=(2 * BT - 1,)).tolist(),
         dict(temperature=0.8, seed=101)),
        (rng.randint(0, 61, size=(2 * BT,)).tolist(),
         dict(temperature=1.1, top_k=7, seed=102)),
        (rng.randint(0, 61, size=(2 * BT + 1,)).tolist(),
         dict(temperature=0.9, top_p=0.7, seed=103)),
        (rng.randint(0, 61, size=(2 * BT,)).tolist(),
         dict(temperature=0.0, seed=104)),          # greedy rides along
    ]
    new = 9  # crosses the next block boundary mid-decode
    batched_eng = _engine().start()
    reqs = [Request(p, max_new_tokens=new, **kw) for p, kw in rows]
    for r in reqs:
        batched_eng.batcher.submit(r)
    batched = [r.result(timeout=300) for r in reqs]
    batched_eng.stop()

    # A DIFFERENT engine (fresh pool, width-1 batches): cross-engine
    # replay exactness and batched==single in one storm.
    single_eng = _engine(replica_id="sampling-single").start()
    singles = [single_eng.generate(p, max_new_tokens=new, **kw)
               for p, kw in rows]
    assert batched == singles
    # Replay with the same seed reproduces; a different seed diverges.
    assert single_eng.generate(rows[0][0], max_new_tokens=new,
                               **rows[0][1]) == batched[0]
    other = single_eng.generate(rows[0][0], max_new_tokens=new,
                                temperature=0.8, seed=999)
    single_eng.stop()
    assert other != batched[0]


# -- n>1 CoW-forked n-best ---------------------------------------------------

def test_fork_shares_prompt_blocks_cow_counts_and_zero_leaks():
    n = 3
    prompt = list(np.random.RandomState(2).randint(
        0, 61, size=(2 * BT + 3,)))  # 2 full blocks + a partial
    eng = _engine(max_batch=8, num_blocks=32,
                  replica_id="fork-t").start()
    req = Request([int(t) for t in prompt], max_new_tokens=5,
                  temperature=0.9, n=n, seed=77)
    # Admission cost: the full prompt blocks are counted ONCE, each fork
    # privately owns only the partial tail + its decode region.
    base = eng._request_cost_blocks(Request([int(t) for t in prompt],
                                            max_new_tokens=5))
    cost = eng._request_cost_blocks(req)
    shared_full = len(prompt) // BT
    assert cost == base + (n - 1) * (base - shared_full)
    assert cost < n * base
    eng.batcher.submit(req)
    out = req.result(timeout=300)
    kv = eng.kv_stats()
    # CoW really engaged: n-1 forked sequences, each forking the shared
    # partial prompt block on its first divergent append.
    assert kv["seq_forks"] == n - 1
    assert kv["forked_requests"] == 1
    assert kv["cow"] >= n - 1
    # Peak pool footprint strictly below n independent sequences' cost.
    assert kv["used_peak"] <= cost < n * base
    # Zero leaked refs once the family retired (prefix-retained blocks
    # are refcount-0 by definition and excluded from `used`).
    assert kv["used"] == 0
    # All n completions present; sample 0 is the legacy surface; each
    # sample is bit-identical to a single run with the same (seed, i)
    # stream — sample 0 shares the request seed's stream exactly.
    assert len(req.samples) == n and all(s for s in req.samples)
    assert out == req.samples[0]
    single = eng.generate([int(t) for t in prompt], max_new_tokens=5,
                          temperature=0.9, seed=77)
    assert req.samples[0] == single
    eng.stop()


def test_fork_primary_finishing_first_never_aliases_blocks():
    """Review regression: the primary retiring on its FIRST token (n>1,
    max_new_tokens=1) must not free the shared prompt blocks before the
    other forks take their references — a ref on a free-listed block
    aliases it with the next allocation.  The BlockManager invariant
    free + retained + used == total (with used >= 0) detects the
    duplicate free-list entries deterministically."""
    eng = _engine(max_batch=8, num_blocks=32, prefix_cache=False,
                  replica_id="fork-first").start()
    prompt = [int(t) for t in
              np.random.RandomState(5).randint(0, 61, size=(BT + 3,))]
    req = Request(prompt, max_new_tokens=1, temperature=0.8, n=3, seed=11)
    eng.batcher.submit(req)
    req.result(timeout=300)
    assert all(len(s) == 1 for s in req.samples)
    kv = eng.kv_stats()
    assert kv["used"] == 0
    assert kv["free"] + kv["retained"] == kv["total"]
    # The pool still behaves after churn (no aliased allocations).
    out1 = eng.generate(prompt, max_new_tokens=4)
    out2 = eng.generate(prompt, max_new_tokens=4)
    assert out1 == out2
    kv = eng.kv_stats()
    assert kv["used"] == 0 and kv["free"] + kv["retained"] == kv["total"]
    eng.stop()


def test_slot_mode_expiry_reports_request_tokens():
    """Review regression: slot-mode ``_Slot`` carries no per-sequence
    stream — mid-flight expiry must read the request's own token list
    (an AttributeError here would poison-fail EVERY in-flight request
    through _recover instead of expiring one)."""
    import time as _time
    from horovod_tpu.serve import DeadlineExceededError
    from horovod_tpu.serve.engine import _Slot
    eng = InferenceEngine(_mlp_adapter(), max_batch=2, kv_mode="slot",
                          metrics=ServeMetrics(), replica_id="slot-exp")
    req = Request([1, 2], max_new_tokens=8, timeout_s=0.001)
    req.generated = [5, 6]
    _time.sleep(0.01)
    eng._slots[0] = _Slot(req, 4)
    assert eng._expire_inflight() == 1
    assert eng._slots[0] is None
    with pytest.raises(DeadlineExceededError) as e:
        req.result(timeout=5)
    assert "2 token(s)" in str(e.value)
    assert eng.metrics.snapshot()["requests"]["expired"] == 1


def test_retired_member_table_never_double_freed_on_group_preempt():
    """Review regression: a fork member that retires (EOS) leaves its
    FREED table cleared — a later pool-exhaustion preempt of a surviving
    member walks the whole family and must not free it again (a double
    free raises, or silently releases a reallocated block)."""
    from horovod_tpu.serve.engine import _ForkGroup, _Seq
    eng = _engine(max_batch=4, num_blocks=8, replica_id="retire-preempt")
    req = Request([1] * BT, max_new_tokens=4, n=2)
    group = _ForkGroup(req)
    members = []
    for i in range(2):
        m = _Seq(req, 0, eng.blocks.allocate(2), [], admit_seq=0)
        m.group = group
        m.sample_index = i
        m.generated = [7]
        m.length = BT
        m.prompt_pos = BT
        group.seqs.append(m)
        members.append(m)
    group.forked = True
    eng._slots[0], eng._slots[1] = members
    with eng._lock:
        eng._retire_seq(0, members[0])  # one fork hits EOS and retires
    assert members[0].table == []       # freed AND cleared
    eng._preempt(1, members[1])         # exhaustion later picks the family
    kv = eng.kv_stats()
    assert kv["used"] == 0
    assert kv["free"] + kv["retained"] == kv["total"]
    assert req.requeues == 1


def test_fork_tail_reservation_blocks_over_admission():
    """Review regression: the (n-1) fork tails admission COUNTS but does
    not allocate stay RESERVED across admission rounds — a later round
    must not hand those blocks to another request (which would turn
    pool-exhaustion preemption into a steady-state tax on every n>1
    request).  With the reservation, both requests complete with ZERO
    preemptions."""
    eng = _engine(max_batch=8, num_blocks=5, prefix_cache=False,
                  replica_id="reserve-t").start()
    prompt = [int(t) for t in
              np.random.RandomState(6).randint(0, 61, size=(12,))]
    # cost = base 3 (24 positions) + 1 tail * (3 - 1 shared full) = 5:
    # exactly the pool; the fork tail (2 blocks) is reserved, the
    # competitor (2 blocks) must WAIT for the family instead of
    # stealing the reservation.
    big = Request(prompt, max_new_tokens=12, temperature=0.7, n=2, seed=1)
    small = Request([1] * BT, max_new_tokens=8)
    eng.batcher.submit(big)
    eng.batcher.submit(small)
    assert len(big.result(timeout=300)) == 12
    assert len(small.result(timeout=300)) == 8
    snap = eng.metrics.snapshot()
    assert snap["requests"]["preempted"] == 0, snap["requests"]
    kv = eng.kv_stats()
    assert kv["used"] == 0
    assert kv["free"] + kv["retained"] == kv["total"]
    eng.stop()


def test_pool_exhaustion_preempts_whole_fork_group():
    """A fork family is preempted as ONE unit: every member's blocks
    freed, every member slot cleared, the request requeued once."""
    from horovod_tpu.serve.engine import _ForkGroup, _Seq
    eng = _engine(max_batch=4, num_blocks=3,
                  replica_id="exhaust-fork")
    old_req = Request([1] * BT, max_new_tokens=4)
    old_req.generated = [5]
    old = _Seq(old_req, 0, eng.blocks.allocate(2), [], admit_seq=0)
    old.length = BT
    old.prompt_pos = BT
    # The YOUNGEST sequences: a 2-way fork family holding one block.
    fork_req = Request([2] * BT, max_new_tokens=4, n=2)
    group = _ForkGroup(fork_req)
    members = []
    for i in range(2):
        m = _Seq(fork_req, 0, eng.blocks.allocate(1) if i == 0 else [],
                 [], admit_seq=1)
        m.group = group
        m.sample_index = i
        m.generated = [7]
        m.length = BT
        m.prompt_pos = BT
        group.seqs.append(m)
        members.append(m)
    eng._slots[0] = old
    eng._slots[1], eng._slots[2] = members
    group.forked = True
    fork_req.samples = [None, None]
    eng._decode_once_paged()
    # The whole family lost its slots and its block; the request sits
    # requeued ONCE with progress reset; the old sequence decoded on.
    assert eng._slots[1] is None and eng._slots[2] is None
    assert fork_req.requeues == 1
    assert fork_req.samples == [None, None]
    assert all(m.table == [] for m in members)
    assert eng.batcher.depth() == 1
    assert eng.metrics.snapshot()["requests"]["preempted"] == 1
    assert eng.blocks.stats()["used"] == 2  # only the old seq's blocks
    assert len(old_req.generated) == 2


# -- speculative decoding ----------------------------------------------------

def test_spec_greedy_equals_greedy_across_bucket_boundaries():
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 61, size=(L,)).tolist()
               for L in (BT - 1, BT, BT + 1, 2 * BT)]
    new = 10  # crosses block boundaries mid-decode
    plain = _engine(replica_id="plain-g").start()
    base = [plain.generate(p, max_new_tokens=new) for p in prompts]
    plain.stop()
    spec = _engine(spec_k=4, replica_id="spec-g").start()
    reqs = [Request(p, max_new_tokens=new) for p in prompts]
    for r in reqs:
        spec.batcher.submit(r)
    outs = [r.result(timeout=300) for r in reqs]
    snap = spec.metrics.snapshot()
    spec.stop()
    assert outs == base  # bit-identical, batched spec vs single plain
    # The draft/verify machinery really ran and is observable.
    assert snap["spec"]["steps"] > 0
    assert snap["spec"]["drafted"] > 0
    assert snap["spec"]["drafted"] == (snap["spec"]["accepted"]
                                       + snap["spec"]["rejected"])
    assert snap["stage"]["spec"]["count"] >= len(prompts)
    assert snap["spec"]["acceptance_rate"] > 0


def test_spec_rejection_rollback_leaks_zero_refs():
    """Force draft/target divergence (amplified late-layer weights) so
    rejections actually fire, then pin: greedy spec still bit-equals
    greedy, and a rejected draft's extended block-table state rolls back
    with zero leaked refs (pool used == 0 after completion)."""
    _, params = _tiny()
    params = dict(params)
    params["block_1"] = jax.tree.map(lambda a: a * 6.0,
                                     params["block_1"])
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 61, size=(BT + 2,)).tolist()
               for _ in range(3)]
    new = 12
    amp_ad = TransformerAdapter(_TINY, params, block_tokens=BT,
                                draft_layers=1)
    plain = _engine(adapter=amp_ad, replica_id="plain-r").start()
    base = [plain.generate(p, max_new_tokens=new) for p in prompts]
    plain.stop()
    spec = _engine(adapter=amp_ad, spec_k=4,
                   replica_id="spec-r").start()
    outs = [spec.generate(p, max_new_tokens=new) for p in prompts]
    snap = spec.metrics.snapshot()
    kv = spec.kv_stats()
    spec.stop()
    assert outs == base
    assert snap["spec"]["rejected"] > 0, snap["spec"]  # divergence real
    assert kv["used"] == 0  # rejected-draft rollback left nothing behind


def test_spec_sampled_matches_nonspec_sampled_distribution():
    """Sampled speculation preserves the target process distribution:
    the empirical distribution of full sampled sequences under spec must
    match non-spec sampling (two-sample chi-square over a tiny vocab —
    the draws differ mechanically, the law must not).  Deterministic:
    fixed seed set."""
    ad = _mlp_adapter(vocab=7)
    seeds = list(range(5000, 5400))

    def storm(spec_k):
        from horovod_tpu.serve import DynamicBatcher
        eng = InferenceEngine(ad, max_batch=8, kv_mode="paged",
                              batcher=DynamicBatcher(max_queue=1024),
                              metrics=ServeMetrics(), spec_k=spec_k,
                              replica_id=f"dist-{spec_k}").start()
        reqs = [Request([1, 2], max_new_tokens=2, temperature=1.2,
                        top_k=4, seed=s) for s in seeds]
        for r in reqs:
            eng.batcher.submit(r)
        outs = [tuple(r.result(timeout=300)) for r in reqs]
        eng.stop()
        return outs

    plain = storm(0)
    spec = storm(3)
    outcomes = sorted(set(plain) | set(spec))
    c1 = np.array([sum(o == x for o in plain) for x in outcomes], float)
    c2 = np.array([sum(o == x for o in spec) for x in outcomes], float)
    # Two-sample chi-square with pooled expectations.
    pooled = (c1 + c2) / 2
    live = pooled > 0
    chi2 = float((((c1 - pooled) ** 2 + (c2 - pooled) ** 2)
                  / pooled)[live].sum())
    df = int(live.sum()) - 1
    # 99.9th percentile of chi2(df) is under df + 4*sqrt(2*df) + 11 for
    # the df range here — a generous deterministic bound.
    assert chi2 < df + 4 * (2 * df) ** 0.5 + 11, (chi2, df, outcomes)


# -- HTTP surface ------------------------------------------------------------

def _serve_http():
    eng = InferenceEngine(_mlp_adapter(), max_batch=4, kv_mode="paged",
                          metrics=ServeMetrics(), replica_id="replica-0")
    sched = ReplicaScheduler([Replica("replica-0", None, eng)],
                             metrics=eng.metrics).start()
    server = ServeServer(sched)
    port = server.start(port=0, host="127.0.0.1")
    return server, sched, port


def _post(port, payload, timeout=60):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_http_per_field_400s_seed_echo_and_fork_counters():
    server, sched, port = _serve_http()
    try:
        # Per-field strict validation → HTTP 400, each field alone.
        for bad in [{"temperature": -1}, {"temperature": "hot"},
                    {"top_k": 0}, {"top_k": 2.5}, {"top_p": 0},
                    {"top_p": 1.5}, {"n": 0}, {"n": "two"},
                    {"seed": "abc"}, {"seed": 1.5}, {"seed": True}]:
            payload = {"tokens": [1, 2, 3], "max_new_tokens": 3, **bad}
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, payload)
            assert e.value.code == 400, bad
        # The effective seed is echoed on EVERY response; replaying it
        # reproduces a sampled answer bit-for-bit (e2e exactness).
        out = _post(port, {"tokens": [1, 2, 3], "max_new_tokens": 6,
                           "temperature": 0.9})
        assert isinstance(out["seed"], int)
        replay = _post(port, {"tokens": [1, 2, 3], "max_new_tokens": 6,
                              "temperature": 0.9, "seed": out["seed"]})
        assert replay["tokens"] == out["tokens"]
        assert replay["seed"] == out["seed"]
        greedy = _post(port, {"tokens": [1, 2, 3], "max_new_tokens": 3})
        assert isinstance(greedy["seed"], int)  # greedy echoes too
        # n>1: all n completions in the response, sample 0 mirrored on
        # the legacy tokens field, and the fork counters visible on
        # /metrics + healthz from this first forked request.
        nbest = _post(port, {"tokens": [1, 2, 3], "max_new_tokens": 4,
                             "temperature": 1.0, "n": 3, "seed": 9})
        assert nbest["n"] == 3
        assert len(nbest["completions"]) == 3
        assert nbest["tokens"] == nbest["completions"][0]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        assert 'hvd_serve_cow_forks_total{replica="replica-0"} 2' in text
        assert ('hvd_serve_forked_requests_total{replica="replica-0"} 1'
                in text)
        assert "hvd_serve_spec_tokens_total" in text
        health = sched.healthz()
        kvb = health["replicas"][0]["kv_blocks"]
        assert kvb["seq_forks"] == 2
        assert kvb["forked_requests"] == 1
        assert kvb["spec_k"] == 0
        snap = sched.metrics.snapshot()
        assert snap["seq_forks"] == 2
    finally:
        server.stop()
        sched.stop()


def test_drain_resets_fork_family_once():
    """A drained n>1 request travels as ONE unit: returned once, with
    samples and generated progress cleared for clean resubmission."""
    eng = _engine(max_batch=8, num_blocks=32, replica_id="drain-f")
    from horovod_tpu.serve.engine import _ForkGroup, _Seq
    req = Request([1] * (BT + 2), max_new_tokens=4, temperature=0.5,
                  n=2, seed=3)
    group = _ForkGroup(req)
    for i in range(2):
        m = _Seq(req, 0, eng.blocks.allocate(1), [], admit_seq=i)
        m.group = group
        m.sample_index = i
        m.generated = [4 + i]
        group.seqs.append(m)
        eng._slots[i] = m
    group.forked = True
    req.samples = [[9], None]
    inflight = eng.drain()
    assert inflight == [req]  # once, not per member
    assert req.samples == [None, None]
    assert req.generated == [] and req.requeues == 1
    assert eng.blocks.stats()["used"] == 0
