"""Autotune / ParameterManager tests (parameter_manager.h:42-110 contract:
explore during warm-up, converge, freeze; CSV log)."""

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.autotune import ParameterManager


def test_disabled_manager_is_frozen():
    pm = ParameterManager(enabled=False, initial_threshold=64)
    assert pm.converged
    assert pm.fusion_threshold_bytes == 64
    pm.record_sample(100, 1.0)  # no-op
    assert pm.converged


def test_sweep_converges_to_best_candidate(tmp_path):
    log = tmp_path / "autotune.csv"
    pm = ParameterManager(enabled=True, candidates_mb=(1, 2, 4),
                          samples_per_candidate=2, log_path=str(log))
    assert not pm.converged
    # Candidate 0 scores poorly, candidate 1 best, candidate 2 middling.
    scores = {0: 10.0, 1: 0.1, 2: 1.0}  # seconds per 1000 bytes
    for cand in range(3):
        for _ in range(2):
            assert pm.fusion_threshold_bytes == [1, 2, 4][cand] * 1024 * 1024
            pm.record_sample(1000, scores[cand])
    assert pm.converged
    assert pm.fusion_threshold_bytes == 2 * 1024 * 1024  # candidate 1 wins
    content = log.read_text()
    assert "converged threshold=2097152" in content
    pm.close()


def test_eager_gradient_fusion_buckets(hvd8):
    """Eager DistributedOptimizer bucketizes leaves via the native fusion
    planner; numerics must match leaf-by-leaf averaging."""
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    state_params = {f"p{i}": jnp.zeros((3,), jnp.float32) for i in range(5)}
    rng = np.random.RandomState(0)
    grads = {f"p{i}": jnp.asarray(
        np.broadcast_to(rng.randn(3).astype(np.float32), (8, 3)).copy())
        for i in range(5)}
    state = opt.init(state_params)
    updates, _ = opt.update(grads, state, state_params)
    for k, g in grads.items():
        np.testing.assert_allclose(
            np.asarray(updates[k][0]), -np.asarray(g)[0], rtol=1e-5)
