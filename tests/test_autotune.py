"""Autotune / ParameterManager tests (parameter_manager.h:42-110 contract:
explore during warm-up, converge, freeze; CSV log)."""

import os

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.autotune import ParameterManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_disabled_manager_is_frozen():
    pm = ParameterManager(enabled=False, initial_threshold=64)
    assert pm.converged
    assert pm.fusion_threshold_bytes == 64
    pm.record_sample(100, 1.0)  # no-op
    assert pm.converged


def test_sweep_converges_to_best_candidate(tmp_path):
    log = tmp_path / "autotune.csv"
    pm = ParameterManager(enabled=True, candidates_mb=(1, 2, 4),
                          samples_per_candidate=2, log_path=str(log))
    assert not pm.converged
    # Candidate 0 scores poorly, candidate 1 best, candidate 2 middling.
    scores = {0: 10.0, 1: 0.1, 2: 1.0}  # seconds per 1000 bytes
    for cand in range(3):
        for _ in range(2):
            assert pm.fusion_threshold_bytes == [1, 2, 4][cand] * 1024 * 1024
            pm.record_sample(1000, scores[cand])
    assert pm.converged
    assert pm.fusion_threshold_bytes == 2 * 1024 * 1024  # candidate 1 wins
    content = log.read_text()
    assert "converged threshold=2097152" in content
    pm.close()


def test_eager_gradient_fusion_buckets(hvd8):
    """Eager DistributedOptimizer bucketizes leaves via the native fusion
    planner; numerics must match leaf-by-leaf averaging."""
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    state_params = {f"p{i}": jnp.zeros((3,), jnp.float32) for i in range(5)}
    rng = np.random.RandomState(0)
    grads = {f"p{i}": jnp.asarray(
        np.broadcast_to(rng.randn(3).astype(np.float32), (8, 3)).copy())
        for i in range(5)}
    state = opt.init(state_params)
    updates, _ = opt.update(grads, state, state_params)
    for k, g in grads.items():
        np.testing.assert_allclose(
            np.asarray(updates[k][0]), -np.asarray(g)[0], rtol=1e-5)


# -- Gaussian-process Bayesian search (optim/bayesian_optimization.cc) -------

def test_gp_fits_and_predicts():
    from horovod_tpu.optim import GaussianProcess
    x = np.linspace(0, 1, 9)[:, None]
    y = np.sin(3 * x[:, 0])
    gp = GaussianProcess(length_scale=0.3)
    gp.fit(x, y)
    mean, std = gp.predict(x)
    np.testing.assert_allclose(mean, y, atol=0.05)   # interpolates samples
    assert np.all(std[1:-1] < 0.2)
    mean_far, std_far = gp.predict(np.array([[0.5 + 1.5]]))
    assert std_far[0] > std[4]  # extrapolation is less certain


def test_expected_improvement_prefers_unexplored():
    from horovod_tpu.optim import expected_improvement
    mean = np.array([1.0, 1.0])
    std = np.array([0.0, 0.5])
    ei = expected_improvement(mean, std, best=1.0)
    assert ei[1] > ei[0] == 0.0


def test_bayesian_optimizer_finds_peak():
    from horovod_tpu.optim import BayesianOptimizer

    def objective(x):  # peak at 24.5 in [20, 28]
        return -((x - 24.5) ** 2)

    bo = BayesianOptimizer(20, 28)
    for _ in range(14):
        x = bo.suggest()
        bo.observe(x, objective(x))
    assert abs(bo.best() - 24.5) < 0.8


def test_bayes_schedule_deterministic():
    from horovod_tpu.optim import BayesianOptimizer
    a, b = BayesianOptimizer(20, 28), BayesianOptimizer(20, 28)
    for _ in range(8):
        xa, xb = a.suggest(), b.suggest()
        assert xa == xb  # identical histories -> identical suggestions
        a.observe(xa, -(xa - 25) ** 2)
        b.observe(xb, -(xb - 25) ** 2)


def test_parameter_manager_bayes_mode_converges(tmp_path):
    pm = ParameterManager(enabled=True, samples_per_candidate=1,
                          search="bayes", bayes_rounds=10,
                          log_path=str(tmp_path / "bo.csv"))
    # Score model: throughput peaks at 8 MB (2^23 bytes).
    for _ in range(10):
        thr = pm.fusion_threshold_bytes
        score = -abs(np.log2(thr) - 23.0) + 10.0
        pm.record_sample(nbytes=int(score * 1e6), seconds=1.0)
    assert pm.converged
    assert 21.0 <= np.log2(pm.fusion_threshold_bytes) <= 25.0
    assert "converged threshold=" in (tmp_path / "bo.csv").read_text()


def test_parameter_manager_bayes_controller_follower_sync():
    """Multi-controller BO (VERDICT r1 weak #7): the controller publishes
    each round's candidate; a follower fetches them and explores the SAME
    thresholds, converging to the controller's synced decision."""
    published = {}

    def pub(round_, value):
        published[round_] = value

    def fetch(round_):
        return published[round_]

    decided = {}

    def controller_decide(local):
        decided["value"] = local
        return local

    def follower_decide(local):
        return decided["value"]  # rank 0's published decision wins

    ctrl = ParameterManager(enabled=True, samples_per_candidate=1,
                            search="bayes", bayes_rounds=6,
                            decide_fn=controller_decide, candidate_pub=pub)
    fol = ParameterManager(enabled=True, samples_per_candidate=1,
                           search="bayes", bayes_rounds=6,
                           decide_fn=follower_decide, candidate_fetch=fetch)
    for _ in range(6):
        t_c, t_f = ctrl.fusion_threshold_bytes, fol.fusion_threshold_bytes
        assert t_c == t_f  # identical exploration thresholds every round
        score = -abs(np.log2(t_c) - 23.0) + 10.0
        ctrl.record_sample(nbytes=int(score * 1e6), seconds=1.0)
        # Follower's local wall-clock scores differ — they must not matter.
        fol.record_sample(nbytes=int(score * 0.7e6), seconds=1.0)
    assert ctrl.converged and fol.converged
    assert fol.fusion_threshold_bytes == ctrl.fusion_threshold_bytes


BAYES_WORKER = """
import jax
jax.config.update('jax_platforms','cpu')
import sys, os; sys.path.insert(0, {repo!r})
import numpy as np
import jax.numpy as jnp, optax
import horovod_tpu as hvd
hvd.init()
grads = {{f"p{{i}}": jnp.ones((64, 64)) for i in range(6)}}
params = jax.tree_util.tree_map(jnp.zeros_like, grads)
opt = hvd.DistributedOptimizer(optax.sgd(0.01))
state = opt.init(params)
pm = hvd.core._state.param_manager
steps = 0
while not pm.converged and steps < 80:
    u, state = opt.update(grads, state, params)
    jax.block_until_ready(u)
    steps += 1
print(f"rank{{hvd.rank()}} BAYES converged={{pm.converged}} "
      f"threshold={{pm.fusion_threshold_bytes}}")
"""


@pytest.mark.integration
@pytest.mark.xdist_group("heavy_e2e")
def test_bayes_autotune_two_processes(tmp_path):
    """End-to-end: 2-process bayes autotune converges to ONE threshold on
    both ranks (rank-0 GP + published candidates + synced decision)."""
    import re
    import subprocess
    import sys
    script = tmp_path / "bayes.py"
    script.write_text(BAYES_WORKER.format(repo=REPO))
    env = dict(os.environ)
    env.update({"HOROVOD_AUTOTUNE": "1",
                "HOROVOD_AUTOTUNE_SEARCH": "bayes",
                "HOROVOD_AUTOTUNE_BAYES_ROUNDS": "4"})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    m = re.findall(r"rank(\d) BAYES converged=(\w+) threshold=(\d+)",
                   proc.stdout)
    assert len(m) == 2, proc.stdout
    assert all(c == "True" for _, c, _ in m), m
    assert len({t for _, _, t in m}) == 1, m  # same threshold on both ranks
