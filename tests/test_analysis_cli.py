"""PASSES-registry contract + ``--all`` mode (ISSUE 17 satellite).

Five analyzer families share ONE CLI front end (analysis/cli.py).  This
file pins the contract pieces that belong to the registry itself rather
than to any single pass:

* mutual exclusion — ``--race --mem`` etc. is a usage error (exit 2);
* prefix ``--select``/``--ignore`` reaches every family uniformly
  (``--select HVD4`` routes to the comm rules and nothing else);
* ``--comm`` honors the exact text / ``--format json`` / exit 0-1-2 /
  pragma contract the other passes already test for themselves;
* ``--all`` runs every registered pass over one shared walk, prints
  combined per-pass output, and exits with the MAX of per-pass exits.
"""

import json

import pytest

from horovod_tpu.analysis.cli import PASSES, build_parser, main as cli_main

DIRTY_COMM = """\
import jax
from jax.sharding import PartitionSpec as P

def step(x):
    a = jax.lax.with_sharding_constraint(x, P("dp"))
    b = jax.lax.with_sharding_constraint(x, P(None, "tp"))
    return a + b
"""

DIRTY_LINT = """\
import horovod_tpu as hvd

def train():
    if hvd.rank() == 0:
        hvd.allreduce_("x", 1.0)
"""

CLEAN = "x = 1\n"


@pytest.fixture()
def corpus(tmp_path):
    (tmp_path / "dirty_comm.py").write_text(DIRTY_COMM)
    (tmp_path / "dirty_lint.py").write_text(DIRTY_LINT)
    (tmp_path / "clean.py").write_text(CLEAN)
    return tmp_path


# ---------------------------------------------------------------------------
# Registry contract
# ---------------------------------------------------------------------------

def test_registry_has_all_five_families():
    assert list(PASSES) == ["lint", "race", "mem", "comm"]
    # lint is the default pass (no flag); the other three get --<name>
    ranges = {name: p.rules for name, p in PASSES.items()}
    assert ranges["comm"] == "HVD400-HVD404"
    assert ranges["mem"] == "HVD300-HVD304"


def test_pass_flags_are_mutually_exclusive(capsys):
    parser = build_parser()
    for combo in (["--race", "--mem"], ["--comm", "--race"],
                  ["--all", "--comm"]):
        with pytest.raises(SystemExit) as e:
            parser.parse_args(combo + ["."])
        assert e.value.code == 2
        capsys.readouterr()


def test_prefix_select_routes_to_comm_family_only(corpus, capsys):
    # HVD4 prefix → the comm pass fires on the comm corpus...
    assert cli_main(["--comm", "--select", "HVD4", str(corpus)]) == 1
    # ...a non-member rule id selects nothing there...
    assert cli_main(["--comm", "--select", "HVD404", str(corpus)]) == 0
    # ...and the same prefix under the lint pass matches no lint rule.
    assert cli_main(["--select", "HVD4", str(corpus)]) == 0
    assert cli_main(["--comm", "--ignore", "HVD4", str(corpus)]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# --comm single-pass contract (text / json / exits / pragma)
# ---------------------------------------------------------------------------

def test_comm_text_output_and_exit_one(corpus, capsys):
    rc = cli_main(["--comm", str(corpus)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD400" in out
    assert "dirty_comm.py:6" in out
    assert "hvdlint: 1 finding(s)" in out


def test_comm_clean_file_exits_zero(corpus, capsys):
    assert cli_main(["--comm", str(corpus / "clean.py")]) == 0
    capsys.readouterr()


def test_comm_json_schema(corpus, capsys):
    rc = cli_main(["--comm", "--format", "json", str(corpus)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["pass"] == "comm"
    assert payload["summary"]["by_rule"] == {"HVD400": 1}
    (f,) = payload["findings"]
    assert (f["rule"], f["line"]) == ("HVD400", 6)
    assert f["path"].endswith("dirty_comm.py")


def test_comm_unreadable_path_is_finding_not_crash(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert cli_main(["--comm", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "HVD000" in out


def test_comm_nonexistent_path_exits_one(capsys):
    assert cli_main(["--comm", "/nonexistent/hvdshard/path"]) == 1
    capsys.readouterr()


def test_comm_pragma_suppression_and_show_suppressed(tmp_path, capsys):
    src = DIRTY_COMM.replace(
        '    b = jax.lax.with_sharding_constraint(x, P(None, "tp"))',
        '    b = jax.lax.with_sharding_constraint(x, P(None, "tp"))'
        '  # hvdlint: disable=HVD400')
    f = tmp_path / "sup.py"
    f.write_text(src)
    assert cli_main(["--comm", str(f)]) == 0
    assert "1 suppressed" in capsys.readouterr().out
    cli_main(["--comm", "--show-suppressed", str(f)])
    assert "HVD400" in capsys.readouterr().out


def test_list_rules_includes_hvd4xx(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("HVD400", "HVD401", "HVD402", "HVD403", "HVD404",
                 "HVD011"):
        assert rule in out, rule


# ---------------------------------------------------------------------------
# --all combined mode
# ---------------------------------------------------------------------------

def test_all_exit_is_max_of_pass_exits(corpus, capsys):
    """Corpus dirties lint AND comm; race/mem are clean — combined exit
    is 1, and the per-pass blocks each report their own counts."""
    rc = cli_main(["--all", str(corpus)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "hvdlint [lint]: 1 finding(s)" in out
    assert "hvdlint [race]: 0 finding(s)" in out
    assert "hvdlint [mem]: 0 finding(s)" in out
    assert "hvdlint [comm]: 1 finding(s)" in out


def test_all_clean_tree_exits_zero(tmp_path, capsys):
    (tmp_path / "ok.py").write_text(CLEAN)
    assert cli_main(["--all", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for name in PASSES:
        assert f"hvdlint [{name}]: 0 finding(s)" in out


def test_all_json_combines_per_pass_payloads(corpus, capsys):
    rc = cli_main(["--all", "--format", "json", str(corpus)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["pass"] == "all"
    assert set(payload["passes"]) == set(PASSES)
    assert payload["passes"]["comm"]["summary"]["by_rule"] == \
        {"HVD400": 1}
    assert payload["passes"]["lint"]["summary"]["total"] == 1
    assert payload["passes"]["race"]["summary"]["total"] == 0


def test_all_select_narrows_every_pass(corpus, capsys):
    """--select HVD4 under --all: only the comm family can fire, so the
    lint finding disappears and the exit reflects comm alone."""
    rc = cli_main(["--all", "--select", "HVD4", str(corpus)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "hvdlint [lint]: 0 finding(s)" in out
    assert "hvdlint [comm]: 1 finding(s)" in out
    assert cli_main(["--all", "--ignore", "HVD0,HVD4",
                     str(corpus)]) == 0
    capsys.readouterr()
