"""Adasum numerics vs a NumPy model of the reference recursion.

Mirrors test/parallel/test_adasum_pytorch.py / test_adasum_tensorflow.py:
the reference validates its recursive halving-doubling against an explicit
model of the pairwise combine math (adasum.h:396-409).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ops import adasum as A
from horovod_tpu.ops import collective_ops as C
from tests.test_collective_ops import run_spmd

N = 8


def np_pair_combine(a, b):
    a = a.astype(np.float64)
    b = b.astype(np.float64)
    dot = np.sum(a * b)
    na = np.sum(a * a)
    nb = np.sum(b * b)
    acoeff = 1.0 - dot / (2 * na) if na > 0 else 1.0
    bcoeff = 1.0 - dot / (2 * nb) if nb > 0 else 1.0
    return acoeff * a + bcoeff * b


def np_adasum_tree(tensors):
    """Binary-tree reduction matching the distance-doubling recursion."""
    level = [t.astype(np.float64) for t in tensors]
    while len(level) > 1:
        level = [np_pair_combine(level[i], level[i + 1])
                 for i in range(0, len(level), 2)]
    return level[0]


def test_pair_combine_parallel_gradients_average():
    # Identical tensors: dot = ||a||^2 = ||b||^2 → coeffs 1/2 → average = a.
    a = np.random.RandomState(0).randn(16).astype(np.float32)
    out = np.asarray(A.pair_combine(jnp.asarray(a), jnp.asarray(a)))
    np.testing.assert_allclose(out, a, rtol=1e-5)


def test_pair_combine_orthogonal_gradients_sum():
    # Orthogonal tensors: dot = 0 → coeffs 1 → plain sum.
    a = np.zeros(8, np.float32); a[0] = 3.0
    b = np.zeros(8, np.float32); b[1] = 4.0
    out = np.asarray(A.pair_combine(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(out, a + b, rtol=1e-6)


def test_pair_combine_zero_operand_identity():
    a = np.random.RandomState(1).randn(8).astype(np.float32)
    z = np.zeros(8, np.float32)
    np.testing.assert_allclose(
        np.asarray(A.pair_combine(jnp.asarray(a), jnp.asarray(z))), a,
        rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(A.pair_combine(jnp.asarray(z), jnp.asarray(a))), a,
        rtol=1e-6)


def test_adasum_allreduce_pow2_matches_numpy_tree(hvd8):
    rng = np.random.RandomState(3)
    per_rank = rng.randn(N, 12).astype(np.float32)
    out = run_spmd(hvd8, lambda x: A.adasum_allreduce(x),
                   jnp.asarray(per_rank))
    expected = np_adasum_tree(list(per_rank))
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r]), expected, rtol=1e-4)


def test_adasum_allreduce_all_ranks_agree(hvd8):
    rng = np.random.RandomState(4)
    per_rank = rng.randn(N, 7, 3).astype(np.float32)
    out = np.asarray(run_spmd(hvd8, lambda x: A.adasum_allreduce(x),
                              jnp.asarray(per_rank)))
    for r in range(1, N):
        np.testing.assert_allclose(out[r], out[0], rtol=1e-6)


def test_adasum_subset_members(hvd8):
    rng = np.random.RandomState(5)
    per_rank = rng.randn(N, 6).astype(np.float32)
    members = (0, 2, 4)  # non-pow2 → gather+tree fallback with zero padding
    out = run_spmd(
        hvd8, lambda x: A.adasum_allreduce(x, members=members),
        jnp.asarray(per_rank))
    expected = np_adasum_tree([per_rank[0], per_rank[2], per_rank[4],
                               np.zeros(6, np.float32)])
    for r in members:
        np.testing.assert_allclose(np.asarray(out[r]), expected, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(out[1]), per_rank[1], rtol=1e-6)


def test_adasum_via_reduce_op_dispatch(hvd8):
    rng = np.random.RandomState(6)
    per_rank = rng.randn(N, 10).astype(np.float32)
    out = run_spmd(hvd8, lambda x: C.allreduce(x, C.Adasum),
                   jnp.asarray(per_rank))
    expected = np_adasum_tree(list(per_rank))
    np.testing.assert_allclose(np.asarray(out[0]), expected, rtol=1e-4)


def test_adasum_eager(hvd8):
    rng = np.random.RandomState(7)
    stacked = jnp.asarray(rng.randn(N, 9).astype(np.float32))
    out = hvd8.allreduce(stacked, op=hvd.Adasum)
    expected = np_adasum_tree(list(np.asarray(stacked)))
    np.testing.assert_allclose(np.asarray(out[0]), expected, rtol=1e-4)


def test_per_slice_adasum_equals_per_layer_adasum(hvd8):
    """per_slice_axis0 over a stacked [L, ...] leaf must equal running the
    plain butterfly on each layer slice independently — the contract that
    lets scan_layers models keep the reference's per-tensor Adasum
    granularity (adasum.h:396-409) through the stacked layout."""
    L, D = 3, 16
    rng = np.random.RandomState(0)
    # Different per-layer scales so joint vs per-layer coefficients differ.
    per_rank = (rng.randn(N, L, D) *
                np.array([1, 10, 100])[None, :, None]).astype(np.float32)
    x = jnp.asarray(per_rank)  # [N, L, D]: stacked over ranks

    per_slice = np.asarray(run_spmd(
        hvd8, lambda s: A.adasum_allreduce(s, per_slice_axis0=True), x))
    for layer in range(L):
        per_layer = np.asarray(run_spmd(
            hvd8, lambda s: A.adasum_allreduce(s),
            jnp.asarray(per_rank[:, layer])))
        np.testing.assert_allclose(per_slice[0, layer], per_layer[0],
                                   rtol=1e-5, atol=1e-4)
    # And per-slice must DIFFER from the joint-coefficient result (the
    # granularity bug it prevents).
    joint = np.asarray(run_spmd(
        hvd8, lambda s: A.adasum_allreduce(s), x))
    assert not np.allclose(joint, per_slice)


def test_adasum_acc_dtype_knob_f64_beats_f32_on_bf16_islands(monkeypatch):
    """HVD_ADASUM_ACC_DTYPE (TODO.md robustness item: the reference
    accumulates its dot/norm islands in DOUBLE, adasum.h:357-363; ours
    default to f32).  On bf16-quantized near-parallel gradients — the
    regime where acoeff = 1 - dot/(2||a||^2) catastrophically cancels —
    the f64 islands must land (much) closer to the f64 NumPy model of the
    reference than the f32 islands do.

    Inputs are bf16-quantized VALUES carried in f64 arrays so the output
    cast (pair_combine returns a.dtype) does not quantize away the island
    error being measured; x64 is enabled for the duration and restored."""
    n = 1 << 15
    rng = np.random.RandomState(11)
    jax.config.update("jax_enable_x64", True)
    try:
        # bf16-quantize adversarial mixed-magnitude, near-parallel pair.
        scale = np.where(np.arange(n) % 2, 1e3, 1e-3)
        a_bf = jnp.asarray(rng.randn(n) * scale, jnp.bfloat16)
        b_bf = jnp.asarray(np.asarray(a_bf, np.float64) * 1.0003
                           + rng.randn(n) * scale * 1e-4, jnp.bfloat16)
        a = jnp.asarray(np.asarray(a_bf, np.float64))  # exact bf16 values
        b = jnp.asarray(np.asarray(b_bf, np.float64))
        expected = np_pair_combine(np.asarray(a), np.asarray(b))
        ref_norm = np.linalg.norm(expected)

        monkeypatch.setenv("HVD_ADASUM_ACC_DTYPE", "f32")
        err32 = np.linalg.norm(
            np.asarray(A.pair_combine(a, b), np.float64) - expected)
        monkeypatch.setenv("HVD_ADASUM_ACC_DTYPE", "f64")
        err64 = np.linalg.norm(
            np.asarray(A.pair_combine(a, b), np.float64) - expected)
    finally:
        jax.config.update("jax_enable_x64", False)
    # f32 islands visibly err on this regime; f64 islands match the
    # reference model to near machine epsilon — orders of magnitude apart.
    assert err32 > 0
    assert err64 < err32 * 1e-2, (err32, err64)
    assert err64 < 1e-9 * ref_norm, (err64, ref_norm)


def test_adasum_acc_dtype_knob_guards(monkeypatch):
    """f64 without x64 falls back to f32 (with a warning, not silence);
    unknown values fail loudly."""
    monkeypatch.setenv("HVD_ADASUM_ACC_DTYPE", "f64")
    assert not jax.config.jax_enable_x64
    assert A._acc_dtype() == jnp.float32  # x64 disabled → fallback
    monkeypatch.setenv("HVD_ADASUM_ACC_DTYPE", "f16")
    with pytest.raises(ValueError, match="HVD_ADASUM_ACC_DTYPE"):
        A._acc_dtype()
    monkeypatch.setenv("HVD_ADASUM_ACC_DTYPE", "f32")
    assert A._acc_dtype() == jnp.float32


def test_per_slice_adasum_subset_members(hvd8):
    """per_slice plumbing through the gathered fallback: a 3-member (non
    power-of-two) process-set Adasum over a stacked leaf must match the
    per-layer NumPy tree model; non-members keep their input."""
    L, D = 2, 8
    members = [1, 4, 6]
    rng = np.random.RandomState(1)
    per_rank = (rng.randn(N, L, D) *
                np.array([1, 50])[None, :, None]).astype(np.float32)

    out = np.asarray(run_spmd(
        hvd8,
        lambda s: A.adasum_allreduce(s, members=members,
                                     per_slice_axis0=True),
        jnp.asarray(per_rank)))
    for layer in range(L):
        expect = np_adasum_tree([per_rank[m, layer] for m in members] +
                                [np.zeros((D,), np.float64)])
        for m in members:
            np.testing.assert_allclose(out[m, layer], expect,
                                       rtol=1e-4, atol=1e-4)
    for r in range(N):
        if r not in members:
            np.testing.assert_allclose(out[r], per_rank[r], atol=1e-6)
