"""ISSUE 16: hvdtier — tiered KV hierarchy (device → host RAM →
KV-server), ahead-of-decode prefetch, cross-replica prefix-block
migration.

Pins the tentpole's contracts layer by layer:

* payload codec — pack/unpack round-trips quantized payloads (int8
  values + float scale rows) bit-exactly;
* TieredBlockManager — pool pressure SPILLS cold retained blocks
  host-ward instead of evicting their bytes, a later same-prefix
  lookup promotes them back bit-identically, ``ensure_writable``
  faults staged payloads in BEFORE the CoW fork, and base retained-LRU
  eviction under the version-salted registry drops the fleet
  directory entry (the roll-mid-migration regression);
* engine — demote-over-preempt admission (in-flight strictly above the
  untiered baseline at the same pool bytes, outputs bit-identical),
  cross-replica migration == local prefill at k*BT±1 prompt tails,
  prefetch-race stalls counted + histogrammed as tier faults, and
  mark_dead unpublishing the dead holder's directory entries;
* faultline — ``delay-tier-fetch`` rides the KV retry backoff and
  merely slows the migration; a ``drop-tier-block`` train past the
  retry budget degrades to recompute with BIT-IDENTICAL output.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import faultline as fl
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.runner.http_server import KVStoreClient, KVStoreServer
from horovod_tpu.serve import (InferenceEngine, Request, TierClient,
                               TierConfig, TieredBlockManager,
                               TransformerAdapter, chain_hashes)
from horovod_tpu.serve.tiering import (HostTier, pack_payload,
                                       unpack_payload)

BT = 8

_TINY = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_len=64, causal=True,
                          dtype=jnp.float32, scan_layers=False)


@pytest.fixture(scope="module")
def tiny_params():
    model = Transformer(_TINY)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture()
def kv_world(monkeypatch):
    monkeypatch.setenv("HVD_KV_RETRY_MAX", "3")
    monkeypatch.setenv("HVD_KV_RETRY_BASE_MS", "1")
    monkeypatch.setenv("HVD_KV_RETRY_CAP_MS", "5")
    server = KVStoreServer()
    port = server.start(0)
    yield server, port
    fl.uninstall()
    server.stop()


def _engine(params, rid, tier=None, client=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("prefill_chunk", 16)
    kw.setdefault("num_blocks", 32)
    ad = TransformerAdapter(_TINY, params, block_tokens=BT,
                            kv_dtype=kw.pop("kv_dtype", None))
    return InferenceEngine(ad, kv_mode="paged", replica_id=rid,
                           tiering=tier, tier_client=client, **kw)


def _tier_client(port, rid):
    return TierClient(KVStoreClient("127.0.0.1", port), replica_id=rid)


def _wait_published(eng, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if eng.kv_stats()["tier"]["published"] >= n:
            return True
        time.sleep(0.02)
    return False


# -- payload codec ------------------------------------------------------------

def test_pack_unpack_payload_bit_exact_with_scale_rows():
    """The serialization a block crosses tiers through must be a byte
    identity — int8 value planes AND their float32 scale rows."""
    rng = np.random.RandomState(0)
    payload = {
        "k": rng.randint(-128, 128, (2, BT, 2, 16)).astype(np.int8),
        "v": rng.randint(-128, 128, (2, BT, 2, 16)).astype(np.int8),
        "k_scale": rng.rand(2, BT, 2).astype(np.float32),
        "v_scale": rng.rand(2, BT, 2).astype(np.float32),
    }
    back = unpack_payload(pack_payload(payload))
    assert sorted(back) == sorted(payload)
    for key in payload:
        assert back[key].dtype == payload[key].dtype
        assert back[key].shape == payload[key].shape
        assert np.array_equal(back[key], payload[key]), key


def test_host_tier_lru_capacity_and_salt_scoping():
    ht = HostTier(2)
    from horovod_tpu.serve.tiering import _HostEntry

    def entry(salt):
        return _HostEntry({"k": np.zeros((1,), np.int8)}, salt, step=0)

    ht.put(1, entry(7))
    ht.put(2, entry(7))
    ht.put(3, entry(9))           # capacity 2: hash 1 LRU-evicted
    assert not ht.contains(1) and ht.evictions == 1
    assert ht.contains(2) and ht.contains(3)
    ht.drop_salt(7)               # roll: only salt-7 copies go
    assert not ht.contains(2) and ht.contains(3)


# -- TieredBlockManager -------------------------------------------------------

def _fake_pool(nb, nkeys=2):
    """A host-side stand-in for the device pool: per-block payload dicts
    with int8 values + float32 scale rows, and extract/insert closures
    over it (what make_block_io wires for a real engine)."""
    rng = np.random.RandomState(1)
    pool = {bid: {"k": rng.randint(-128, 128, (2, BT, 4)).astype(np.int8),
                  "k_scale": rng.rand(2, BT).astype(np.float32)}
            for bid in range(nb)}

    def extract(bid):
        return {k: a.copy() for k, a in pool[bid].items()}

    def insert(bid, payload):
        pool[bid] = {k: a.copy() for k, a in payload.items()}

    return pool, extract, insert


def test_spill_then_promote_round_trips_bit_exact():
    """Under pool pressure the coldest retained prefix block spills
    host-ward (payload + scale rows) instead of losing its bytes; the
    next same-prefix lookup promotes it back bit-identically and the
    chain hash survives the round trip."""
    bm = TieredBlockManager(4, BT, TierConfig())
    pool, extract, insert = _fake_pool(4)
    bm.set_device_io(extract, insert)
    prompt = list(range(4 * BT))
    hashes = chain_hashes(prompt, BT)
    blocks = bm.allocate(3)
    for h, bid in zip(hashes, blocks):
        bm.register(h, bid, salt=5)
    golden = [extract(bid) for bid in blocks]
    bm.free_table(blocks)                   # retained, not freed
    taken = bm.allocate(4)                  # pressure: all 3 spill
    st = bm.stats()["tier"]
    assert st["spills"] == 3 and st["host_blocks"] == 3
    assert st["spill_bytes"] > 0
    bm.free_table(taken)
    ids, matched = bm.lookup_prefix(prompt, hashes=hashes)
    assert matched == 3 * BT and len(ids) == 3
    for want, bid in zip(golden, ids):
        got = extract(bid)
        for key in want:
            assert np.array_equal(got[key], want[key]), key
    assert bm.stats()["tier"]["promotes"] == 3
    assert bm.stats()["tier"]["host_blocks"] == 0


def test_ensure_writable_faults_staged_payload_in_before_fork():
    """A spilled-and-refetched block whose payload is still STAGED must
    be applied to the device before a CoW fork copies it — otherwise
    the fork would duplicate stale zeros, not the real K/V."""
    bm = TieredBlockManager(4, BT, TierConfig())
    pool, extract, insert = _fake_pool(4)
    bm.set_device_io(extract, insert)
    bid = bm.allocate(1)[0]
    staged = {"k": np.full((2, BT, 4), 7, np.int8),
              "k_scale": np.ones((2, BT), np.float32)}
    bm.note_pending(bid, staged)
    bm.ref(bid)                              # shared → fork must copy
    new_bid, copied = bm.ensure_writable(bid)
    assert copied and new_bid != bid
    # The staged bytes landed on the ORIGINAL block before the fork
    # decision; a fork then copies real contents.
    assert np.array_equal(pool[bid]["k"], staged["k"])
    assert bm.apply_pending(bid) is False    # consumed exactly once


def test_retained_eviction_drops_directory_entry(kv_world):
    """Satellite bugfix: base retained-LRU eviction under the
    version-salted registry must retract the fleet directory entry —
    a peer resolving the evicted hash would otherwise fetch bytes the
    holder no longer has (or worse, rolled-weights bytes)."""
    _, port = kv_world
    client = _tier_client(port, "evict-t")
    bm = TieredBlockManager(2, BT, TierConfig(), client=client)
    prompt = list(range(2 * BT))
    h = chain_hashes(prompt, BT)[0]
    bid = bm.allocate(1)[0]
    bm.register(h, bid, salt=3)
    assert bm.mark_publishing(h)
    assert client.publish(h, 3, pack_payload(
        {"k": np.zeros((1, BT), np.int8)}))
    bm.note_published(h, 3, True)
    assert client.lookup(h) is not None
    bm.free(bid)                             # → retained
    # Corruption scrub takes the base eviction path (no extract wired):
    # the hash leaves the registry AND the fleet directory.
    assert bm.invalidate_retained(1) == 1
    assert client.lookup(h) is None
    peer = TieredBlockManager(2, BT, TierConfig(),
                              client=_tier_client(port, "evict-peer"))
    assert peer.remote_hits([h]) == 0


@pytest.mark.slow  # ~9s
def test_roll_mid_migration_misses_and_degrades(kv_world, tiny_params):
    """unpublish_salt (the weight-roll hook) mid-migration: the peer's
    directory probe of the OLD version's chain must miss — it
    re-prefills under its own weights instead of importing stale K/V."""
    _, port = kv_world
    ea = _engine(tiny_params, "roll-a", TierConfig(),
                 _tier_client(port, "roll-a")).start()
    eb = _engine(tiny_params, "roll-b", TierConfig(),
                 _tier_client(port, "roll-b")).start()
    base = _engine(tiny_params, "roll-base").start()
    try:
        shared = list(range(1, 3 * BT + 2))
        ref = base.generate(shared, max_new_tokens=4)
        assert ea.generate(shared, max_new_tokens=4) == ref
        assert _wait_published(ea, 3)
        # The roll retracts every entry published under the old salt.
        salt = ea._prefix_salt(None)
        assert ea.blocks.unpublish_salt(salt) == 3
        got = eb.generate(shared, max_new_tokens=4)
        assert got == ref                    # recompute, bit-identical
        assert eb.kv_stats()["tier"]["migrated_tokens"] == 0
    finally:
        ea.stop(); eb.stop(); base.stop()


# -- engine: demote-over-preempt ---------------------------------------------

@pytest.mark.slow  # ~18s capacity comparison
def test_demote_over_preempt_admits_more_at_same_pool_bytes(tiny_params):
    """The tentpole's perf claim at unit scale: with an identical device
    pool, the tiered engine keeps strictly more requests IN FLIGHT than
    the untiered baseline (which preempts its youngest), and the storm
    is bit-identical to the solo baseline."""
    base = _engine(tiny_params, "dop-base", max_batch=12,
                   num_blocks=16).start()
    tiered = _engine(tiny_params, "dop-tier",
                     TierConfig(oversub=4.0, quantum=2),
                     max_batch=12, num_blocks=16).start()
    try:
        prompts = [np.random.RandomState(100 + i).randint(
            0, 61, (10,)).tolist() for i in range(10)]
        singles = [base.generate(p, max_new_tokens=20) for p in prompts]

        def storm(eng):
            out = [None] * len(prompts)

            def run(i):
                out[i] = eng.generate(prompts[i], max_new_tokens=20)

            ts = [threading.Thread(target=run, args=(i,))
                  for i in range(len(prompts))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return out

        # Baseline first: its peak concurrency is bounded by the pool.
        base_peak = [0]

        def watch():
            while any(r is None for r in base_out):
                with base._lock:
                    live = len({id(s.request) for s in base._slots
                                if s is not None})
                base_peak[0] = max(base_peak[0], live)
                time.sleep(0.001)

        base_out = [None] * len(prompts)

        def run_base(i):
            base_out[i] = base.generate(prompts[i], max_new_tokens=20)

        w = threading.Thread(target=watch)
        ts = [threading.Thread(target=run_base, args=(i,))
              for i in range(len(prompts))]
        w.start()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        w.join()
        assert base_out == singles
        got = storm(tiered)
        assert got == singles                # outputs_match
        st = tiered.kv_stats()["tier"]
        assert st["inflight_peak"] > base_peak[0], \
            (st["inflight_peak"], base_peak[0])
        assert st["swapped_out_seqs"] > 0 and st["swapped_in_seqs"] > 0
    finally:
        base.stop(); tiered.stop()


# -- engine: cross-replica migration -----------------------------------------

@pytest.mark.slow  # ~11s block-boundary sweep
def test_migration_matches_local_prefill_at_block_boundaries(
        kv_world, tiny_params):
    """Follower outputs through migrated prefix blocks == local
    recompute at k*BT-1, k*BT, k*BT+1 prompt tails, and the migrated
    token count lands in the stats."""
    _, port = kv_world
    base = _engine(tiny_params, "mig-base").start()
    ea = _engine(tiny_params, "mig-a", TierConfig(),
                 _tier_client(port, "mig-a")).start()
    eb = _engine(tiny_params, "mig-b", TierConfig(),
                 _tier_client(port, "mig-b")).start()
    try:
        shared = list(range(1, 3 * BT + 2))  # 3 full blocks + tail
        assert ea.generate(shared + [40], max_new_tokens=6) == \
            base.generate(shared + [40], max_new_tokens=6)
        assert _wait_published(ea, 3)
        for tail in ([], [41], [41, 42]):
            p = shared + tail
            assert eb.generate(p, max_new_tokens=6) == \
                base.generate(p, max_new_tokens=6), f"tail={tail}"
        st = eb.kv_stats()["tier"]
        assert st["migrated_tokens"] >= 3 * BT
        assert st["migration_failures"] == 0
        # Migrated tokens count as prefix hits — the same currency as
        # local prefix-cache reuse.
        assert eb.blocks.stats()["prefix_hit_tokens"] >= 3 * BT
    finally:
        base.stop(); ea.stop(); eb.stop()


@pytest.mark.slow  # ~7s
def test_prefetch_race_stall_is_counted_and_histogrammed(
        kv_world, tiny_params):
    """A delayed tier fetch the decode loop has to WAIT on is exactly
    one tier fault: counted, stall-histogrammed (the p99 contract
    surface), and harmless to the output."""
    _, port = kv_world
    base = _engine(tiny_params, "pf-base").start()
    ea = _engine(tiny_params, "pf-a", TierConfig(),
                 _tier_client(port, "pf-a")).start()
    eb = _engine(tiny_params, "pf-b", TierConfig(),
                 _tier_client(port, "pf-b")).start()
    try:
        shared = list(range(1, 3 * BT + 2))
        ref = base.generate(shared, max_new_tokens=4)
        assert ea.generate(shared, max_new_tokens=4) == ref
        assert _wait_published(ea, 3)
        fl.install(fl.FaultPlan(
            [fl.FaultSpec("delay-tier-fetch", step=0, repeat=3,
                          param=0.05)]))
        assert eb.generate(shared, max_new_tokens=4) == ref
        snap = eb.metrics.snapshot()["tier"]
        assert eb.kv_stats()["tier"]["faults"] >= 1
        assert snap["faults"] >= 1
        assert snap["fault_stall"]["count"] >= 1
        assert snap["fault_stall"]["p50_ms"] > 0
    finally:
        fl.uninstall()
        base.stop(); ea.stop(); eb.stop()


@pytest.mark.slow  # ~8s
def test_drop_tier_block_train_degrades_to_recompute_bit_identical(
        kv_world, tiny_params):
    """Satellite soak: a drop train longer than the KV retry budget
    kills the migration fetch — the follower recomputes the prefix
    locally and the answer is BIT-IDENTICAL to the never-migrated
    run."""
    _, port = kv_world
    base = _engine(tiny_params, "drop-base").start()
    ea = _engine(tiny_params, "drop-a", TierConfig(),
                 _tier_client(port, "drop-a")).start()
    eb = _engine(tiny_params, "drop-b", TierConfig(),
                 _tier_client(port, "drop-b")).start()
    try:
        shared = list(range(1, 3 * BT + 2))
        ref = base.generate(shared, max_new_tokens=6)
        assert ea.generate(shared, max_new_tokens=6) == ref
        assert _wait_published(ea, 3)
        # retry_max=3 (kv_world): a train of 9 exhausts every block's
        # budget however the fetches interleave.
        fl.install(fl.FaultPlan(
            [fl.FaultSpec("drop-tier-block", step=0, repeat=9)]))
        assert eb.generate(shared, max_new_tokens=6) == ref
        st = eb.kv_stats()["tier"]
        assert st["migration_failures"] >= 1
        assert st["fetch_drops"] >= 3
        assert st["migrated_tokens"] == 0
    finally:
        fl.uninstall()
        base.stop(); ea.stop(); eb.stop()


def test_mark_dead_unpublishes_directory_entries(kv_world, tiny_params):
    """A dead replica's directory entries must not outlive it: after
    the mark_dead hook runs, a peer's fleet probe misses and admission
    plans NO migration toward the dead holder."""
    _, port = kv_world
    ea = _engine(tiny_params, "dead-a", TierConfig(),
                 _tier_client(port, "dead-a")).start()
    try:
        shared = list(range(1, 3 * BT + 2))
        ea.generate(shared, max_new_tokens=4)
        assert _wait_published(ea, 3)
        hashes = chain_hashes(shared, BT, salt=ea._prefix_salt(None))
        peer = TieredBlockManager(4, BT, TierConfig(),
                                  client=_tier_client(port, "dead-peer"))
        assert peer.remote_hits(hashes[:3]) == 3
        assert ea.tier_unpublish() == 3      # the mark_dead hook
        fresh = TieredBlockManager(4, BT, TierConfig(),
                                   client=_tier_client(port, "dead-p2"))
        assert fresh.remote_hits(hashes[:3]) == 0
    finally:
        ea.stop()


# -- batcher / surfaces -------------------------------------------------------

def test_batcher_peek_is_nonconsuming_and_copies(tiny_params):
    eng = _engine(tiny_params, "peek-t")
    b = eng.batcher
    b.submit(Request([1, 2, 3], max_new_tokens=1))
    b.submit(Request([4, 5], max_new_tokens=1))
    head = b.peek(8)
    assert [p for p, _ in head] == [[1, 2, 3], [4, 5]]
    head[0][0][0] = 99                       # caller mutation is local
    again = b.peek(1)
    assert again[0][0] == [1, 2, 3]
    assert len(b.drain()) == 2               # nothing was consumed


def test_tier_metrics_exposition(kv_world, tiny_params):
    _, port = kv_world
    eng = _engine(tiny_params, "met-t", TierConfig(),
                  _tier_client(port, "met-t")).start()
    try:
        eng.generate(list(range(1, 2 * BT + 2)), max_new_tokens=4)
        snap = eng.metrics.snapshot()
        assert "tier" in snap
        for key in ("faults", "fault_stall", "spill_bytes",
                    "promote_bytes", "demote_bytes", "migrations",
                    "migrated_tokens"):
            assert key in snap["tier"], key
        text = eng.metrics.render()
        for needle in ("hvd_serve_tier_fault_stall_ms",
                       "hvd_serve_tier_faults_total",
                       "hvd_serve_tier_bytes_total",
                       "hvd_serve_tier_migrations_total"):
            assert needle in text, needle
        stats = eng.kv_stats()
        assert stats["tier"]["published"] >= 0
        assert "inflight_peak" in stats["tier"]
    finally:
        eng.stop()
