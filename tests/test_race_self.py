"""Self-race-analysis regression gate: the repo must stay hvdrace-clean.

The analog of tests/test_lint_self.py for the lock-order &
thread-lifecycle analysis (analysis/lockgraph.py): runs ``--race`` over
``horovod_tpu/`` + ``examples/`` in-process and fails on ANY unsuppressed
HVD2xx finding — a new AB/BA lock nesting, a blocking call smuggled into
a critical section, or an unjoined non-daemon thread fails tier-1 before
it can deadlock a fleet.

To silence a deliberate pattern, add ``# hvdlint: disable=HVD2xx`` on the
flagged line WITH a reasoned comment, or declare the intended order with
``# hvdrace: order=A<B`` (docs/static_analysis.md).
"""

import os

from horovod_tpu.analysis import lint_paths, race_paths, unsuppressed
from horovod_tpu.analysis.cli import main as cli_main

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PATHS = [os.path.join(_REPO, "horovod_tpu"),
          os.path.join(_REPO, "examples")]


def test_repo_is_hvdrace_clean():
    findings = race_paths(_PATHS)
    active = unsuppressed(findings)
    assert not active, (
        "hvdrace found lock-order / thread-lifecycle antipatterns — fix "
        "them, declare the intended order with '# hvdrace: order=A<B', "
        "or suppress each with a reasoned '# hvdlint: disable=...' "
        "comment:\n" + "\n".join(f.format() for f in active))


def test_race_suppressions_are_auditable():
    """Every suppressed hvdrace finding still surfaces with
    suppressed=True (the audit trail the dogfooding requires), and the
    repo carries at least the negotiation flush-under-lock audit."""
    findings = race_paths(_PATHS)
    for f in findings:
        assert f.suppressed, f.format()
    assert any("negotiation" in f.path and f.rule == "HVD201"
               for f in findings), \
        "the audited flush-under-lock suppression disappeared"


def test_race_walk_covers_the_threaded_tree():
    """Guard the gate itself: the analyzer must actually index the
    threaded subsystems' locks — if the walk or the lock indexing ever
    silently breaks, zero findings would mean nothing."""
    from horovod_tpu.analysis.lockgraph import _Analyzer
    from horovod_tpu.analysis.linter import iter_python_files
    import ast

    analyzer = _Analyzer()
    files = iter_python_files(_PATHS)
    assert len(files) > 50
    # The Pallas paged-attention module (ISSUE 8) must stay inside the
    # race walk: it is lock-free BY DESIGN (pure kernels), and that
    # property is only checked if the walker actually visits it.
    assert any(f.endswith(os.path.join("serve", "paged_attention.py"))
               for f in files), "serve/paged_attention.py not analyzed"
    # The tracing plane (ISSUE 9) holds its own lock while called from
    # under the engine/batcher locks — its ordering must stay analyzed.
    for mod in ("tracing.py", "merge.py"):
        assert any(f.endswith(os.path.join("obs", mod))
                   for f in files), f"obs/{mod} not analyzed"
    # The hvdmem analyzer (ISSUE 10) is lock-free by design (pure AST +
    # jaxpr walks) — a property only checked if the walk visits it.
    assert any(f.endswith(os.path.join("analysis", "memplan.py"))
               for f in files), "analysis/memplan.py not analyzed"
    # The sampling layer (ISSUE 11) is lock-free by design (pure key
    # derivation + filtering called from under the engine's loop) —
    # checked only if the walker visits it.
    assert any(f.endswith(os.path.join("serve", "sampling.py"))
               for f in files), "serve/sampling.py not analyzed"
    # The fleet controller (ISSUE 13) polls replica locks from its own
    # thread — the walker must see it for the registry check below.
    assert any(f.endswith(os.path.join("serve", "controller.py"))
               for f in files), "serve/controller.py not analyzed"
    # The registry's roll walk (ISSUE 15) drains replicas while holding
    # its own lock; tenancy's DRR is called under the batcher's.
    assert any(f.endswith(os.path.join("serve", "registry.py"))
               for f in files), "serve/registry.py not analyzed"
    assert any(f.endswith(os.path.join("serve", "tenancy.py"))
               for f in files), "serve/tenancy.py not analyzed"
    assert any(f.endswith(os.path.join("serve", "tiering.py"))
               for f in files), "serve/tiering.py not analyzed"
    # The SP world (ISSUE 20) is lock-FREE by design — every mutation
    # happens on the engine loop thread; that property only holds if
    # the race walker actually visits it.
    assert any(f.endswith(os.path.join("serve", "seqpar.py"))
               for f in files), "serve/seqpar.py not analyzed"
    # The hvdroute front door (ISSUE 18) runs forwards, hedges, and the
    # active health poller on their own threads over the router lock.
    for mod in ("router.py", "router_server.py"):
        assert any(f.endswith(os.path.join("serve", mod))
                   for f in files), f"serve/{mod} not analyzed"
    # The hvdshard analyzer (ISSUE 17) is lock-free by design (pure AST
    # + jaxpr walks) — checked only if the walker visits it.
    assert any(f.endswith(os.path.join("analysis", "shardplan.py"))
               for f in files), "analysis/shardplan.py not analyzed"
    for path in files:
        with open(path, "rb") as fh:
            src = fh.read().decode("utf-8", errors="replace")
        try:
            analyzer.add_module(ast.parse(src, filename=path), path, src)
        except SyntaxError:  # pragma: no cover - repo parses
            pass
    analyzer.run()
    # The serve/elastic control plane's locks must be in the registry
    # under their class identities.
    for label in ("DynamicBatcher._lock", "ServeMetrics._lock",
                  "InferenceEngine._lock", "ReplicaScheduler._lock",
                  "BlockManager._lock", "ElasticDriver._lock",
                  "Negotiator._buf_lock", "Negotiator._flush_lock",
                  "Tracer._lock", "FleetController._lock",
                  "ModelRegistry._lock", "TieredBlockManager._lock",
                  "Router._lock", "RouterMetrics._lock"):
        assert label in analyzer.lock_sites, \
            f"{label} missing from the witness registry"
    # Condition-wraps-lock aliasing: the batcher's _cond must NOT appear
    # as a separate lock (it IS _lock).
    assert "DynamicBatcher._cond" not in analyzer.lock_sites
    # The engine's lock participates in observed ordering edges.
    assert any("InferenceEngine._lock" in k for k in analyzer.graph), \
        "no ordering edges recorded for the engine lock"


def test_analyzer_modules_are_hvdlint_clean():
    """lockgraph.py and witness.py must themselves pass the hvdlint the
    rest of the repo is held to (test_lint_self covers the tree; this
    pins the two new modules explicitly per the CI satellite)."""
    targets = [os.path.join(_REPO, "horovod_tpu", "analysis", m)
               for m in ("lockgraph.py", "witness.py")]
    for t in targets:
        assert os.path.exists(t)
    assert not unsuppressed(lint_paths(targets))


def test_race_cli_exit_contract_matches_hvdlint(tmp_path, capsys):
    """--race honors the exact 0/1/2 contract hvdlint defines: 0 clean,
    1 findings (incl. HVD000 parse failures), same paths, same flags."""
    clean = tmp_path / "clean.py"
    clean.write_text("import threading\n\n"
                     "def go():\n"
                     "    threading.Thread(target=print, daemon=True)"
                     ".start()\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import threading\n\n"
                     "def go():\n"
                     "    threading.Thread(target=print).start()\n")
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")

    for args, expected in (
            ([str(clean)], 0),
            ([str(dirty)], 1),
            ([str(bad)], 1),
            (["/nonexistent/race/path"], 1)):
        rc_race = cli_main(["--race"] + args)
        capsys.readouterr()
        assert rc_race == expected, (args, rc_race)
    # The lint mode agrees on the parse-failure and missing-path classes
    # (finding, not crash) — one shared contract.
    for args in ([str(bad)], ["/nonexistent/race/path"]):
        rc_lint = cli_main(args)
        capsys.readouterr()
        rc_race = cli_main(["--race"] + args)
        capsys.readouterr()
        assert rc_lint == rc_race == 1


def test_race_cli_dogfood_command_exits_zero(capsys):
    """The acceptance command: python -m horovod_tpu.analysis --race
    horovod_tpu (in-process — same code path as the module entry)."""
    rc = cli_main(["--race", os.path.join(_REPO, "horovod_tpu")])
    capsys.readouterr()
    assert rc == 0
