"""serve/ unit tests: batcher triggers/backpressure, engine continuous
batching + KV-cache exactness, replica routing/failover, metrics.

The e2e acceptance path (HTTP server over a multi-replica process-set
world, preemption-marker failover under concurrent load) lives in
tests/test_serve_e2e.py; this file pins each layer in isolation.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.models import create_mlp
from horovod_tpu.models.transformer import (Transformer, TransformerConfig,
                                            stack_block_params)
from horovod_tpu.serve import (DeadlineExceededError, DynamicBatcher,
                               Histogram, InferenceEngine, MLPAdapter,
                               NoHealthyReplicaError, QueueFullError,
                               Replica, ReplicaScheduler, Request,
                               ServeMetrics, TransformerAdapter,
                               bucket_requests, prompt_bucket)

VOCAB = 31


# -- shared tiny models ------------------------------------------------------

def _mlp_adapter(seed=3, vocab=VOCAB, max_len=128):
    mlp = create_mlp(features=(16, vocab))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, vocab)))["params"]
    return MLPAdapter(mlp, params, vocab_size=vocab, max_len=max_len)


def _mlp_chain(adapter, prompt, n):
    """Ground truth for the MLP Markov chain."""
    seq = []
    tok = prompt[-1]
    for _ in range(n):
        tok = int(adapter._apply(np.asarray([tok], np.int32))[0])
        seq.append(tok)
    return seq


_TINY = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_len=64, causal=True,
                          dtype=jnp.float32, scan_layers=False)


def _tiny_transformer(seed=0):
    model = Transformer(_TINY)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


# -- batcher -----------------------------------------------------------------

def test_prompt_bucketing_pow2_with_floor_and_cap():
    assert prompt_bucket(1, floor=8) == 8
    assert prompt_bucket(8, floor=8) == 8
    assert prompt_bucket(9, floor=8) == 16
    assert prompt_bucket(100, floor=8, cap=64) == 64
    groups = bucket_requests([Request([1] * n) for n in (3, 8, 9, 30)],
                             floor=8)
    assert sorted(groups) == [8, 16, 32]
    assert len(groups[8]) == 2


def test_batcher_backpressure_sheds_at_capacity():
    b = DynamicBatcher(max_queue=2, max_wait_ms=1000)
    b.submit(Request([1]))
    b.submit(Request([2]))
    with pytest.raises(QueueFullError):
        b.submit(Request([3]))
    assert b.depth() == 2


def test_batcher_size_trigger_fires_immediately():
    b = DynamicBatcher(max_queue=16, max_wait_ms=10_000)
    for i in range(4):
        b.submit(Request([i + 1]))
    t0 = time.monotonic()
    got = b.get_admission(4, block_s=5.0)
    assert len(got) == 4
    assert time.monotonic() - t0 < 1.0  # did not wait out max_wait


def test_batcher_deadline_trigger_returns_partial_batch():
    b = DynamicBatcher(max_queue=16, max_wait_ms=30)
    b.submit(Request([1]))
    t0 = time.monotonic()
    got = b.get_admission(8, block_s=5.0)  # size trigger can't fire
    waited = time.monotonic() - t0
    assert [len(r.prompt) for r in got] == [1]
    assert 0.01 < waited < 2.0  # released by the deadline trigger


def test_batcher_expired_requests_are_shed_not_returned():
    shed = []
    b = DynamicBatcher(max_queue=16, max_wait_ms=1,
                       on_shed=lambda r, why: shed.append(why))
    r = Request([1], timeout_s=0.01)
    b.submit(r)
    time.sleep(0.05)
    assert b.get_admission(4, block_s=0.0) == []
    with pytest.raises(DeadlineExceededError):
        r.result(timeout=1)
    assert shed == ["expired"]


def test_batcher_requeue_front_bypasses_bound_and_orders_first():
    b = DynamicBatcher(max_queue=1, max_wait_ms=0)
    b.submit(Request([1]))
    drained = [Request([7]), Request([8])]
    b.requeue_front(drained)  # over capacity on purpose
    got = b.get_admission(3, block_s=0.0)
    assert [r.prompt for r in got] == [[7], [8], [1]]


# -- metrics -----------------------------------------------------------------

def test_histogram_quantiles_and_render():
    h = Histogram(buckets_ms=(1.0, 10.0, 100.0))
    for v in (0.5, 5, 5, 50):
        h.observe(v)
    assert h.count == 4 and h.quantile(0.5) == 10.0
    m = ServeMetrics()
    m.observe_ttft(12.0)
    m.observe_decode_step(3.0, occupancy=5, new_tokens=5)
    m.count_request("ok")
    text = m.render()
    assert "hvd_serve_ttft_ms_bucket" in text
    assert "hvd_serve_batch_occupancy_max 5" in text
    assert 'hvd_serve_requests_total{outcome="ok"} 1' in text
    snap = m.snapshot()
    # 5 decode-step tokens + the prefill's first token (observe_ttft).
    assert snap["tokens_total"] == 6 and snap["occupancy"]["max"] == 5


def test_metrics_timeline_counters(tmp_path):
    import json
    from horovod_tpu.timeline import Timeline
    path = str(tmp_path / "serve_trace.json")
    tl = Timeline(path)
    m = ServeMetrics()
    m.set_timeline(tl)
    m.observe_decode_step(2.0, occupancy=3, new_tokens=3)
    m.maybe_emit_timeline(force=True)
    tl.close()
    events = json.load(open(path))
    serve = [e for e in events if e.get("name", "").startswith("SERVE/")]
    assert serve and serve[0]["ph"] == "C"
    assert serve[0]["args"]["occupancy"] == 3
    assert serve[0]["args"]["tokens_total"] == 3


# -- engine (MLP adapter: pure mechanics) ------------------------------------

def test_engine_generate_matches_markov_chain():
    ad = _mlp_adapter()
    eng = InferenceEngine(ad, max_batch=4, replica_id="t").start()
    try:
        out = eng.generate([5, 9], max_new_tokens=10)
        assert out == _mlp_chain(ad, [5, 9], 10)
    finally:
        eng.stop()


def test_engine_eos_stops_generation():
    ad = _mlp_adapter()
    chain = _mlp_chain(ad, [5], 10)
    eos = chain[3]
    eng = InferenceEngine(ad, max_batch=2, replica_id="t").start()
    try:
        out = eng.generate([5], max_new_tokens=10, eos_id=eos)
        assert out == chain[:4]  # stops AT the eos token, inclusive
    finally:
        eng.stop()


def test_engine_batched_equals_single_and_occupancy_exceeds_one():
    ad = _mlp_adapter()
    eng = InferenceEngine(ad, max_batch=8, replica_id="t").start()
    try:
        prompts = [[(i * 7) % VOCAB or 1] for i in range(16)]
        singles = [eng.generate(p, max_new_tokens=12) for p in prompts]
        results = [None] * 16

        def run(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=12)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == singles
        assert eng.metrics.snapshot()["occupancy"]["max"] > 1
    finally:
        eng.stop()


class _SlowAdapter:
    """Delegating adapter whose decode steps take ~5 ms — keeps requests
    demonstrably in-flight for drain/failover tests."""

    def __init__(self, inner, delay_s=0.005):
        self._inner = inner
        self._delay = delay_s
        self.vocab_size = inner.vocab_size
        self.max_len = inner.max_len

    def init_cache(self, max_batch):
        return self._inner.init_cache(max_batch)

    def prefill(self, cache, prompts, slots):
        return self._inner.prefill(cache, prompts, slots)

    def decode(self, cache, tokens, positions):
        time.sleep(self._delay)
        return self._inner.decode(cache, tokens, positions)


def test_engine_drain_returns_inflight_with_cleared_progress():
    ad = _SlowAdapter(_mlp_adapter())
    eng = InferenceEngine(ad, max_batch=4, replica_id="t").start()
    reqs = [Request([3], max_new_tokens=120) for _ in range(3)]
    for r in reqs:
        eng.batcher.submit(r)
    deadline = time.monotonic() + 10
    while eng.active_count < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    drained = eng.drain()
    assert sorted(r.request_id for r in drained) == \
        sorted(r.request_id for r in reqs)
    for r in drained:
        assert r.generated == [] and r.requeues == 1 and not r.done
    assert eng.active_count == 0


def test_engine_survives_poisoned_batch():
    """An adapter exception mid-step must FAIL the in-flight requests
    with the real error (not hang them to client timeout) and leave the
    engine serving — one poisoned batch must not take the replica down."""

    class _PoisonOnce(_SlowAdapter):
        def __init__(self, inner):
            super().__init__(inner, delay_s=0.0)
            self.armed = True

        def decode(self, cache, tokens, positions):
            if self.armed:
                self.armed = False
                raise RuntimeError("simulated device fault")
            return super().decode(cache, tokens, positions)

    ad = _PoisonOnce(_mlp_adapter())
    eng = InferenceEngine(ad, max_batch=2, replica_id="t").start()
    try:
        doomed = Request([5], max_new_tokens=8)
        eng.batcher.submit(doomed)
        with pytest.raises(RuntimeError, match="simulated device fault"):
            doomed.result(timeout=30)
        # The loop recovered: a fresh request completes correctly.
        out = eng.generate([5], max_new_tokens=8)
        assert out == _mlp_chain(_mlp_adapter(), [5], 8)
        assert eng.metrics.snapshot()["requests"]["error"] == 1
    finally:
        eng.stop()


def test_engine_rejects_overlong_request():
    ad = _mlp_adapter(max_len=16)
    eng = InferenceEngine(ad, max_batch=2, replica_id="t").start()
    try:
        r = Request([1] * 10, max_new_tokens=10)  # 20 > max_len 16
        eng.batcher.submit(r)
        with pytest.raises(ValueError, match="exceeds max_len"):
            r.result(timeout=10)
    finally:
        eng.stop()


# -- transformer adapter -----------------------------------------------------

def test_transformer_prefill_matches_flax_apply():
    model, params = _tiny_transformer()
    ad = TransformerAdapter(_TINY, params)
    ad._max_batch = 4
    cache = ad.init_cache(4)
    tokens = np.random.RandomState(0).randint(0, 61, (1, 12))
    ref = model.apply({"params": params},
                      jnp.asarray(tokens, jnp.int32))  # [1, 12, V]
    cache, first = ad.prefill(cache, [tokens[0].tolist()], [0])
    assert int(first[0]) == int(jnp.argmax(ref[0, -1]))


def test_transformer_decode_matches_full_recompute_greedy():
    model, params = _tiny_transformer()

    def flax_greedy(prompt, n):
        seq = list(prompt)
        for _ in range(n):
            lg = model.apply({"params": params},
                             jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(lg[0, -1])))
        return seq[len(prompt):]

    eng = InferenceEngine(TransformerAdapter(_TINY, params),
                          max_batch=4, replica_id="t").start()
    try:
        for seed in (0, 1):
            prompt = np.random.RandomState(seed).randint(
                0, 61, (5 + seed * 7,)).tolist()
            assert eng.generate(prompt, max_new_tokens=6) == \
                flax_greedy(prompt, 6)
    finally:
        eng.stop()


def test_transformer_adapter_accepts_scan_layers_checkpoints():
    """A scan_layers (stacked blocks/block) checkpoint is unstacked at
    load and decodes identically to the unrolled layout."""
    _, params = _tiny_transformer()
    stacked = stack_block_params(params, _TINY.num_layers)
    e1 = InferenceEngine(TransformerAdapter(_TINY, params),
                         max_batch=2, replica_id="a").start()
    e2 = InferenceEngine(TransformerAdapter(_TINY, stacked),
                         max_batch=2, replica_id="b").start()
    try:
        prompt = [3, 17, 42, 9]
        assert e1.generate(prompt, max_new_tokens=5) == \
            e2.generate(prompt, max_new_tokens=5)
    finally:
        e1.stop()
        e2.stop()


def test_transformer_adapter_rejects_training_mesh_configs():
    import dataclasses
    _, params = _tiny_transformer()
    with pytest.raises(ValueError, match="data-parallel"):
        TransformerAdapter(dataclasses.replace(_TINY, seq_parallel="ring"),
                           params)


def test_transformer_prefill_compile_cache_buckets():
    """Same-bucket shapes reuse the compiled prefill; only new (count,
    length) buckets compile — steady-state serving never recompiles."""
    _, params = _tiny_transformer()
    ad = TransformerAdapter(_TINY, params)
    ad._max_batch = 8
    cache = ad.init_cache(8)
    cache, _ = ad.prefill(cache, [[1, 2, 3]], [0])
    assert set(ad._prefill_cache) == {(1, 8)}
    cache, _ = ad.prefill(cache, [[4] * 7], [1])  # same buckets
    assert set(ad._prefill_cache) == {(1, 8)}
    cache, _ = ad.prefill(cache, [[5] * 9], [2])  # longer prompt bucket
    assert set(ad._prefill_cache) == {(1, 8), (1, 16)}
    cache, _ = ad.prefill(cache, [[6]] * 3, [3, 4, 5])  # wider count bucket
    assert set(ad._prefill_cache) == {(1, 8), (1, 16), (4, 8)}


# -- process-set partitioning ------------------------------------------------

def test_partition_process_sets_even_and_ragged(hvd8):
    sets = hvd.partition_process_sets(4)
    assert [s.ranks for s in sets] == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert all(s.process_set_id is not None for s in sets)
    ragged = hvd.partition_process_sets(3)
    assert [s.ranks for s in ragged] == [[0, 1, 2], [3, 4, 5], [6, 7]]
    with pytest.raises(ValueError):
        hvd.partition_process_sets(9)
    with pytest.raises(ValueError):
        hvd.partition_process_sets(0)


# -- replica scheduler -------------------------------------------------------

def _two_replica_sched():
    replicas = []
    metrics = ServeMetrics()
    for i in range(2):
        eng = InferenceEngine(_mlp_adapter(), max_batch=4,
                              metrics=metrics, replica_id=f"replica-{i}")
        replicas.append(Replica(f"replica-{i}", None, eng))
    return ReplicaScheduler(replicas, metrics=metrics).start()


def test_scheduler_routes_least_loaded():
    sched = _two_replica_sched()
    try:
        # Saturate replica-0's queue by hand; new work must go to 1.
        sched.replicas[0].engine.stop()  # freeze so load stays put
        for _ in range(5):
            sched.replicas[0].engine.batcher.submit(
                Request([1], max_new_tokens=1))
        r = Request([2], max_new_tokens=1)
        target = sched.submit(r)
        assert target.replica_id == "replica-1"
        assert r.result(timeout=30) == _mlp_chain(_mlp_adapter(), [2], 1)
    finally:
        sched.stop()


def test_scheduler_mark_dead_requeues_to_survivor():
    replicas, metrics = [], ServeMetrics()
    for i in range(2):
        eng = InferenceEngine(_SlowAdapter(_mlp_adapter()), max_batch=4,
                              metrics=metrics, replica_id=f"replica-{i}")
        replicas.append(Replica(f"replica-{i}", None, eng))
    sched = ReplicaScheduler(replicas, metrics=metrics).start()
    try:
        victim = sched.replicas[0]
        reqs = [Request([3], max_new_tokens=100) for _ in range(3)]
        for r in reqs:
            victim.engine.batcher.submit(r)
        deadline = time.monotonic() + 10
        while victim.engine.active_count < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        sched.mark_dead("replica-0", reason="test")
        assert sched.healthz()["status"] == "degraded"
        chain = _mlp_chain(_mlp_adapter(), [3], 100)
        for r in reqs:
            assert r.result(timeout=60) == chain
            assert r.replica_id == "replica-1" and r.requeues == 1
        assert sched.metrics.snapshot()["requests"]["requeued"] == 3
    finally:
        sched.stop()


def test_mark_dead_requeues_past_full_survivor_queue():
    """Review finding: drained work must bypass the survivors' capacity
    bound (requeue_front), never shed — a replica loss with full queues
    must not turn accepted requests into 503s."""
    metrics = ServeMetrics()
    replicas = []
    for i in range(2):
        eng = InferenceEngine(_SlowAdapter(_mlp_adapter()),
                              batcher=DynamicBatcher(max_queue=1),
                              max_batch=2, metrics=metrics,
                              replica_id=f"replica-{i}")
        replicas.append(Replica(f"replica-{i}", None, eng))
    sched = ReplicaScheduler(replicas, metrics=metrics).start()
    try:
        victim = sched.replicas[0]
        survivor = sched.replicas[1]
        # Fill the survivor's queue to its (tiny) capacity.
        survivor.engine.batcher.submit(Request([9], max_new_tokens=30))
        reqs = [Request([3], max_new_tokens=30) for _ in range(3)]
        for r in reqs:
            victim.engine.batcher.requeue_front([r])  # direct: bypass route
        deadline = time.monotonic() + 10
        while victim.engine.active_count == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        sched.mark_dead("replica-0", reason="test")
        chain = _mlp_chain(_mlp_adapter(), [3], 30)
        for r in reqs:  # every accepted request completes, none shed
            assert r.result(timeout=60) == chain
        assert metrics.snapshot()["requests"]["shed"] == 0
        assert metrics.snapshot()["requests"]["requeued"] == 3
    finally:
        sched.stop()


def test_scheduler_stop_fails_inflight_promptly():
    """Review finding: stop() must fail in-flight requests immediately —
    not leave their waiters parked until the request timeout."""
    metrics = ServeMetrics()
    eng = InferenceEngine(_SlowAdapter(_mlp_adapter()), max_batch=2,
                          metrics=metrics, replica_id="replica-0")
    sched = ReplicaScheduler([Replica("replica-0", None, eng)],
                             metrics=metrics).start()
    r = Request([5], max_new_tokens=120)
    sched.submit(r)
    deadline = time.monotonic() + 10
    while eng.active_count == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    t0 = time.monotonic()
    sched.stop()
    with pytest.raises(NoHealthyReplicaError, match="shutting down"):
        r.result(timeout=5)
    assert time.monotonic() - t0 < 5.0


def test_engine_counts_expired_requests_in_metrics():
    """Review finding: deadline sheds inside the engine's own batcher
    must surface as the 'expired' outcome."""
    eng = InferenceEngine(_mlp_adapter(), max_batch=2, replica_id="t")
    r = Request([5], max_new_tokens=4, timeout_s=0.01)
    eng.batcher.submit(r)
    time.sleep(0.05)
    eng.start()
    try:
        with pytest.raises(DeadlineExceededError):
            r.result(timeout=10)
        deadline = time.monotonic() + 5
        while eng.metrics.snapshot()["requests"]["expired"] == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.metrics.snapshot()["requests"]["expired"] == 1
    finally:
        eng.stop()


def test_metrics_scrape_during_expiry_storm_no_deadlock():
    """Review finding: /metrics sampling queue depth (metrics lock →
    batcher lock) while the engine sheds expired requests (batcher lock →
    metrics lock via on_shed) was an AB/BA deadlock.  Hammer both sides
    concurrently; everything must settle well inside the budget."""
    eng = InferenceEngine(_mlp_adapter(), max_batch=2, replica_id="t")
    eng.metrics.register_queue_depth("t", eng.batcher.depth)
    eng.start()
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            eng.metrics.render()
            eng.metrics.snapshot()
            eng.metrics.maybe_emit_timeline(force=True)

    scrapers = [threading.Thread(target=scraper) for _ in range(2)]
    for t in scrapers:
        t.start()
    try:
        reqs = []
        for i in range(60):
            r = Request([5], max_new_tokens=2,
                        timeout_s=0.001 if i % 2 else None)
            try:
                eng.batcher.submit(r)
                reqs.append(r)
            except QueueFullError:
                pass
        deadline = time.monotonic() + 30
        done = [False] * len(reqs)
        for i, r in enumerate(reqs):
            try:
                r.result(timeout=max(deadline - time.monotonic(), 0.1))
                done[i] = True
            except DeadlineExceededError:
                done[i] = True  # expired — also a settled outcome
        assert all(done)
        assert time.monotonic() < deadline
    finally:
        stop.set()
        for t in scrapers:
            t.join(timeout=10)
        eng.stop()


def test_request_rejects_nonpositive_max_new_tokens():
    with pytest.raises(ValueError, match="max_new_tokens"):
        Request([1], max_new_tokens=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        Request([1], max_new_tokens=-3)


def test_scheduler_unserving_when_all_dead():
    sched = _two_replica_sched()
    try:
        sched.mark_dead("replica-0")
        sched.mark_dead("replica-1")
        assert sched.healthz()["status"] == "unserving"
        with pytest.raises(NoHealthyReplicaError):
            sched.submit(Request([1]))
    finally:
        sched.stop()


def test_report_rank_lost_maps_rank_to_replica(hvd8):
    from horovod_tpu.serve import build_replicas
    sched = build_replicas(_mlp_adapter, num_replicas=4).start()
    try:
        assert [r.ranks for r in sched.replicas] == \
            [[0, 1], [2, 3], [4, 5], [6, 7]]
        assert sched.report_rank_lost(5) == "replica-2"
        assert sched.report_rank_lost(99) is None
        # Second loss of the same replica's other rank: already dead.
        assert sched.report_rank_lost(4) is None
        health = sched.healthz()
        assert health["status"] == "degraded" and health["healthy"] == 3
    finally:
        sched.stop()
