"""hvdctl (ISSUE 13): fleet-controller decision tables, QoS admission
tiers, brownout enforcement, and the load-aware Retry-After hint.

The tentpole's testability contract is that ``decide()`` is a PURE
function over (config, state, snapshot, now) — the tables here replay
synthetic stage-latency / queue-depth / kv-headroom sequences through it
and pin every transition (scale-up, scale-down, brownout rungs,
hysteresis, cooldowns) with no fleet, no HTTP, no threads.  The
controller's actuation side (mark_alive / mark_dead / brownout
propagation onto real batchers and engines) gets a small integration
smoke on an UNstarted replica pair; the full closed loop under seeded
diurnal load runs in tests/test_ctl_soak.py.
"""

import threading
import time
import types

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu import faultline
from horovod_tpu.faultline import FaultPlan, FaultSpec, diurnal_load
from horovod_tpu.models import create_mlp
from horovod_tpu.serve import (ControllerConfig, ControllerState,
                               DynamicBatcher, FleetController,
                               FleetSnapshot, InferenceEngine, MLPAdapter,
                               QueueFullError, Replica, ReplicaScheduler,
                               Request, ServeMetrics)
from horovod_tpu.serve.controller import (BROWNOUT_MAX_LEVEL, decide,
                                          windowed_p99)

VOCAB = 31


def _mlp_adapter(seed=3):
    mlp = create_mlp(features=(16, VOCAB))
    params = mlp.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, VOCAB)))["params"]
    return MLPAdapter(mlp, params, vocab_size=VOCAB, max_len=128)


def _cfg(**kw):
    """Fast-reacting config for the tables; tests override per-case."""
    base = dict(poll_s=0.1, min_replicas=1, max_replicas=8,
                queue_high=8.0, queue_low=1.0, up_polls=3, down_polls=4,
                up_cooldown_s=0.0, down_cooldown_s=0.0,
                brownout_polls=2, brownout_clear_polls=3)
    base.update(kw)
    return ControllerConfig(**base).validate()


def _hot(healthy=2, spares=1, queued=100, **kw):
    return FleetSnapshot(healthy=healthy, spares=spares, queued=queued,
                         **kw)


def _idle(healthy=2, spares=1, queued=0, **kw):
    return FleetSnapshot(healthy=healthy, spares=spares, queued=queued,
                         **kw)


def _run(cfg, snaps, state=None, t0=0.0, dt=1.0):
    """Replay a snapshot sequence through decide(); returns the action
    list per poll (the table format every test below asserts on)."""
    state = state or ControllerState()
    return state, [decide(cfg, state, s, t0 + i * dt)
                   for i, s in enumerate(snaps)]


# -- decide(): scale-up ------------------------------------------------------

def test_scale_up_after_sustained_pressure_only():
    cfg = _cfg(up_polls=3)
    _, actions = _run(cfg, [_hot()] * 4)
    assert actions == [[], [], ["scale_up"], []]


def test_pressure_blip_resets_hysteresis():
    # 2 hot polls, one dead-band poll (neither hot nor idle), 2 more hot:
    # the counter restarted, so no scale-up yet.
    cfg = _cfg(up_polls=3)
    blip = [_hot(), _hot(), _hot(queued=8),  # 8/2 = 4: dead band
            _hot(), _hot()]
    _, actions = _run(cfg, blip)
    assert actions == [[], [], [], [], []]


def test_up_cooldown_blocks_then_fires_immediately_on_expiry():
    cfg = _cfg(up_polls=2, up_cooldown_s=3.5)
    state, actions = _run(cfg, [_hot()] * 8, dt=1.0)
    # Fires at t=1 (2nd hot poll), cooldown blocks t=2..4 (< 1+3.5),
    # fires again the very first eligible poll (t=5) without needing a
    # fresh up_polls run — hot_polls is deliberately not reset while the
    # cooldown holds the action back.
    assert actions == [[], ["scale_up"], [], [], [],
                       ["scale_up"], [], []]
    assert state.last_scale_up_t == 5.0


@pytest.mark.parametrize("snap", [
    # Each pressure source alone must trip the controller: queue depth,
    # windowed latency-tier p99 >= SLO, kv headroom under the floor.
    _hot(queued=100),
    FleetSnapshot(healthy=2, spares=1, queued=0, latency_p99_ms=900.0),
    FleetSnapshot(healthy=2, spares=1, queued=0,
                  kv_headroom_bytes=1 << 10),
])
def test_every_pressure_source_scales_up(snap):
    cfg = _cfg(up_polls=2, slo_ms=500.0, headroom_min_bytes=1 << 20)
    _, actions = _run(cfg, [snap] * 2)
    assert actions == [[], ["scale_up"]]


def test_disabled_slo_and_headroom_are_ignored():
    cfg = _cfg(up_polls=1, slo_ms=0.0, headroom_min_bytes=0)
    snap = FleetSnapshot(healthy=2, spares=1, queued=0,
                         latency_p99_ms=10_000.0, kv_headroom_bytes=1)
    _, actions = _run(cfg, [snap] * 3)
    assert actions == [[], [], []]


# -- decide(): scale-down ----------------------------------------------------

def test_scale_down_after_sustained_idleness():
    cfg = _cfg(down_polls=4)
    _, actions = _run(cfg, [_idle()] * 5)
    assert actions == [[], [], [], ["scale_down"], []]


def test_scale_down_guards_min_replicas():
    cfg = _cfg(down_polls=2, min_replicas=2)
    _, actions = _run(cfg, [_idle(healthy=2)] * 6)
    assert all(a == [] for a in actions)


def test_scale_down_cooldown():
    cfg = _cfg(down_polls=2, down_cooldown_s=3.5, min_replicas=1)
    _, actions = _run(cfg, [_idle(healthy=4)] * 9, dt=1.0)
    # Fires at t=1, cooldown blocks t=2..4 (< 1+3.5), fires again the
    # first eligible poll (t=5) — the idle counter keeps accumulating
    # while the cooldown holds the action back.
    assert actions == [[], ["scale_down"], [], [], [],
                       ["scale_down"], [], [], []]


def test_dead_band_resets_idle_counter():
    cfg = _cfg(down_polls=2)
    _, actions = _run(cfg, [_idle(), _hot(queued=8),  # dead band
                            _idle(), _idle()])
    assert actions == [[], [], [], ["scale_down"]]


# -- decide(): brownout ladder -----------------------------------------------

def test_brownout_only_when_envelope_exhausted():
    # Spares available: pressure scales up, never browns out.
    cfg = _cfg(up_polls=1, brownout_polls=1)
    _, actions = _run(cfg, [_hot(healthy=2, spares=3)] * 4)
    assert all(a == ["scale_up"] for a in actions)


@pytest.mark.parametrize("snap", [
    _hot(healthy=8, spares=3),   # at max_replicas
    _hot(healthy=2, spares=0),   # out of spares
])
def test_brownout_climbs_when_stuck(snap):
    cfg = _cfg(up_polls=2, brownout_polls=2)
    state, actions = _run(cfg, [snap] * 12)
    # up_polls gates entry (stuck counting starts at poll 1), then one
    # rung per brownout_polls stuck observations: rungs at polls 2, 4,
    # 6, 8 — and the ladder stops at BROWNOUT_MAX_LEVEL (no 5th rung).
    fired = [i for i, a in enumerate(actions) if a == ["brownout_up"]]
    assert fired == [2, 4, 6, 8]
    assert state.brownout_level == BROWNOUT_MAX_LEVEL


def test_brownout_descends_with_own_hysteresis_then_scales_down():
    cfg = _cfg(up_polls=1, brownout_polls=1, brownout_clear_polls=3,
               down_polls=2)
    state = ControllerState()
    # Drive to rung 2 (stuck at the envelope: with up_polls and
    # brownout_polls both 1, every hot poll climbs one rung), then
    # clear the pressure.
    _, up = _run(cfg, [_hot(healthy=8, spares=0)] * 2, state=state)
    assert up == [["brownout_up"], ["brownout_up"]]
    assert state.brownout_level == 2
    _, down = _run(cfg, [_idle(healthy=8)] * 7, state=state, t0=100.0)
    # One rung per brownout_clear_polls clear polls; scale_down stays
    # suppressed until the ladder is fully off (level 0 at poll 5 —
    # idle-counter runway then allows the first shrink at that poll).
    assert down == [[], [], ["brownout_down"], [], [],
                    ["brownout_down", "scale_down"], []]
    assert state.brownout_level == 0


def test_brownout_descent_interrupted_by_pressure():
    cfg = _cfg(up_polls=1, brownout_polls=1, brownout_clear_polls=2)
    state = ControllerState()
    _run(cfg, [_hot(healthy=8, spares=0)], state=state)
    assert state.brownout_level == 1
    # clear, clear-but-then-hot: the clear counter must restart.
    _, actions = _run(cfg, [_idle(healthy=8), _hot(healthy=8, spares=0),
                            _idle(healthy=8), _idle(healthy=8)],
                      state=state, t0=50.0)
    assert actions[0] == [] and actions[1] == ["brownout_up"]
    assert actions[2] == [] and actions[3] == ["brownout_down"]


# -- config + windowed p99 ---------------------------------------------------

def test_controller_config_validate_rejects_bad_envelopes():
    with pytest.raises(ValueError, match="min_replicas"):
        ControllerConfig(min_replicas=0).validate()
    with pytest.raises(ValueError, match="max_replicas"):
        ControllerConfig(min_replicas=4, max_replicas=2).validate()
    with pytest.raises(ValueError, match="hysteresis"):
        ControllerConfig(queue_low=9, queue_high=8).validate()
    with pytest.raises(ValueError, match="poll_s"):
        ControllerConfig(poll_s=0).validate()


def test_controller_config_from_env(monkeypatch):
    monkeypatch.setenv("HVD_SERVE_CTL_SLO_MS", "250")
    monkeypatch.setenv("HVD_SERVE_CTL_MAX_REPLICAS", "12")
    monkeypatch.setenv("HVD_SERVE_CTL_BROWNOUT_MAX_NEW", "48")
    cfg = ControllerConfig.from_env()
    assert (cfg.slo_ms, cfg.max_replicas, cfg.brownout_max_new) == \
        (250.0, 12, 48)


def test_windowed_p99_diffs_cumulative_buckets():
    bounds = [1.0, 5.0, 25.0]
    # Empty window: no observations between polls.
    assert windowed_p99(bounds, [3, 3, 3], [3, 3, 3], 3, 3) is None
    # 3 new observations, all <= 5ms: windowed p99 is 5, even though the
    # CUMULATIVE histogram still remembers an old 25ms spike.
    assert windowed_p99(bounds, [0, 0, 3], [0, 3, 6], 3, 6) == 5.0
    # First poll (no previous counts): whole histogram is the window.
    assert windowed_p99(bounds, None, [0, 0, 4], 0, 4) == 25.0
    # Above the top bucket: clamps to the last bound.
    assert windowed_p99(bounds, [0, 0, 0], [0, 0, 0], 0, 2) == 25.0


# -- QoS tiers in the batcher ------------------------------------------------

def test_request_rejects_unknown_qos_tier():
    with pytest.raises(ValueError, match="qos"):
        Request([1], qos="bulk")


def test_edf_ordering_requeued_then_latency_then_deadline():
    b = DynamicBatcher(max_queue=16, max_wait_ms=0)
    tpt = Request([1], qos="throughput")
    lat_late = Request([2], qos="latency", timeout_s=60)
    lat_soon = Request([3], qos="latency", timeout_s=5)
    lat_fifo = Request([4], qos="latency")  # deadline-less
    redo = Request([5], qos="throughput")
    redo.requeues = 1  # drained off a dead replica
    for r in (tpt, lat_late, lat_soon, lat_fifo):
        b.submit(r)
    b.requeue_front([redo])
    got = b.get_admission(8)
    assert [r.request_id for r in got] == [
        redo.request_id,      # requeued work outranks everything
        lat_soon.request_id,  # EDF within the latency tier
        lat_late.request_id,
        lat_fifo.request_id,  # deadline-less latency after deadlines
        tpt.request_id]       # throughput tier last


def test_deadline_less_single_tier_traffic_keeps_exact_fifo():
    b = DynamicBatcher(max_queue=16, max_wait_ms=0)
    reqs = [Request([i + 1]) for i in range(6)]
    for r in reqs:
        b.submit(r)
    got = b.get_admission(6)
    assert [r.request_id for r in got] == [r.request_id for r in reqs]


def test_per_tier_queue_bounds():
    b = DynamicBatcher(max_queue=16, max_wait_ms=1000)
    b.tier_bounds["throughput"] = 2
    b.submit(Request([1], qos="throughput"))
    b.submit(Request([2], qos="throughput"))
    with pytest.raises(QueueFullError, match="throughput tier"):
        b.submit(Request([3], qos="throughput"))
    b.submit(Request([4], qos="latency"))  # other tier unaffected
    assert b.depth() == 3


# -- brownout rung enforcement ----------------------------------------------

def test_brownout_l1_sheds_new_throughput_submissions():
    b = DynamicBatcher(max_queue=16, max_wait_ms=1000)
    b.brownout_level = 1
    with pytest.raises(QueueFullError, match="throughput tier shed"):
        b.submit(Request([1], qos="throughput"))
    b.submit(Request([2], qos="latency"))  # latency tier unaffected
    assert b.depth() == 1


def test_brownout_l2_caps_max_new_tokens_at_take_time():
    b = DynamicBatcher(max_queue=16, max_wait_ms=0)
    b.submit(Request([1], max_new_tokens=64))
    b.submit(Request([2], max_new_tokens=4))
    b.brownout_max_new = 8
    seen_costs = []

    def cost(r):
        seen_costs.append(r.max_new_tokens)
        return 1

    got = b.get_admission(4, budget=100, cost=cost)
    # Capped BEFORE cost() ran: admission accounting, block allocation,
    # and fork-tail reserves all see the capped lifetime.
    assert [r.max_new_tokens for r in got] == [8, 4]
    assert seen_costs == [8, 4]


def test_brownout_l3_rejects_fork_requests():
    b = DynamicBatcher(max_queue=16, max_wait_ms=1000)
    b.brownout_level = 3
    with pytest.raises(QueueFullError, match="n>1 forking"):
        b.submit(Request([1], temperature=0.5, n=4, seed=7))
    b.submit(Request([2], temperature=0.5, n=1, seed=7))


def test_brownout_l4_purges_queued_throughput_work():
    shed = []
    b = DynamicBatcher(max_queue=16, max_wait_ms=0,
                       on_shed=lambda r, why: shed.append((r, why)))
    lat = Request([1], qos="latency")
    tp1 = Request([2], qos="throughput")
    tp2 = Request([3], qos="throughput")
    for r in (tp1, lat, tp2):
        b.submit(r)
    b.brownout_level = 4
    got = b.get_admission(8)
    assert got == [lat]
    assert sorted(r.request_id for r, _ in shed) == \
        sorted([tp1.request_id, tp2.request_id])
    assert all(why == "shed" for _, why in shed)
    for r in (tp1, tp2):
        with pytest.raises(QueueFullError, match="latency-tier-only"):
            r.result(timeout=1)


# -- load-aware Retry-After (satellite: server hint regression) --------------

def _handler_for(metrics, healthy=2):
    """A detached _ServeHandler with just enough server context for the
    hint math (no sockets — the regression is about the formula)."""
    from horovod_tpu.serve.server import _ServeHandler
    h = object.__new__(_ServeHandler)
    fleet = [types.SimpleNamespace(state="healthy")] * healthy + \
        [types.SimpleNamespace(state="dead")]
    h.server = types.SimpleNamespace(
        metrics=metrics,
        scheduler=types.SimpleNamespace(fleet=lambda: list(fleet)))
    return h


def test_retry_after_derives_from_queue_drain_rate(monkeypatch):
    m = ServeMetrics()
    h = _handler_for(m, healthy=2)
    # No queue and no service history: the old flat hint.
    assert h._retry_after_s() == 1
    # 12 queued x 2s EWMA service time over 2 replicas = 12s, capped at
    # the default HVD_SERVE_RETRY_AFTER_CAP_S=8.
    m.register_queue_depth("r0", lambda: 7)
    m.register_queue_depth("r1", lambda: 5)
    m.observe_request_ms("latency", 2000.0)
    assert h._retry_after_s() == 8
    monkeypatch.setenv("HVD_SERVE_RETRY_AFTER_CAP_S", "30")
    assert h._retry_after_s() == 12
    # Shallower queue: the hint scales down with the drain estimate.
    m.register_queue_depth("r0", lambda: 1)
    m.register_queue_depth("r1", lambda: 1)
    assert h._retry_after_s() == 2


def test_retry_after_capped_by_client_deadline_budget(monkeypatch):
    m = ServeMetrics()
    h = _handler_for(m, healthy=1)
    m.register_queue_depth("r0", lambda: 10)
    m.observe_request_ms("latency", 1000.0)
    monkeypatch.setenv("HVD_SERVE_RETRY_AFTER_CAP_S", "60")
    assert h._retry_after_s() == 10
    # A client with 3s of budget left must not be told to sleep 10.
    headers = dict(h._budget_headers(Request([1], timeout_s=3.0)))
    assert int(headers["Retry-After"]) <= 3
    assert float(headers["X-Deadline-Remaining-S"]) <= 3.0
    # Deadline-less requests get the raw availability hint.
    headers = dict(h._budget_headers(Request([1])))
    assert headers["Retry-After"] == "10"
    assert "X-Deadline-Remaining-S" not in headers


# -- faultline: load-spike + diurnal load shape ------------------------------

def test_diurnal_load_is_seeded_and_diurnal():
    a = diurnal_load(24, peak=40, base=2, seed=9)
    assert a == diurnal_load(24, peak=40, base=2, seed=9)  # pure
    assert a != diurnal_load(24, peak=40, base=2, seed=10)
    assert len(a) == 24 and all(v >= 0 for v in a)
    mid = sum(a[8:16]) / 8
    edges = (sum(a[:4]) + sum(a[-4:])) / 8
    assert mid > edges  # low -> peak -> low
    with pytest.raises(ValueError):
        diurnal_load(0, peak=4)
    with pytest.raises(ValueError):
        diurnal_load(4, peak=2, base=5)
    with pytest.raises(ValueError):
        diurnal_load(4, peak=2, jitter=1.5)


def test_load_spike_spec_defaults_to_ctl_poll_point():
    spec = faultline.parse_spec("load-spike~16*2")
    assert spec.kind == "load-spike"
    assert spec.point == "ctl.poll"
    assert spec.param == 16.0 and spec.repeat == 2


def test_controller_consumes_load_spike_through_injector():
    bursts = []
    sched = types.SimpleNamespace(fleet=lambda: [],
                                  metrics=ServeMetrics())
    ctl = FleetController(sched, config=_cfg(),
                          load_injector=lambda n: bursts.append(n) or n)
    plan = FaultPlan([FaultSpec("load-spike", step=1, repeat=2,
                                param=5.0)], seed=3)
    faultline.install(plan)
    try:
        for _ in range(4):
            ctl.poll()
        assert plan.exhausted()
        assert bursts == [5, 5]
    finally:
        faultline.uninstall()


# -- FleetController integration (real scheduler, unstarted engines) ---------

def _fleet(n=2, metrics=None):
    metrics = metrics or ServeMetrics()
    reps = [Replica(f"replica-{i}", None,
                    InferenceEngine(_mlp_adapter(), max_batch=4,
                                    replica_id=f"replica-{i}"))
            for i in range(n)]
    return ReplicaScheduler(reps, metrics=metrics), reps


def test_controller_revives_dead_spare_then_shrinks_when_idle():
    sched, reps = _fleet(2)
    sched.mark_dead("replica-1", reason="test setup")
    cfg = _cfg(up_polls=1, down_polls=2, queue_high=2.0,
               min_replicas=1, max_replicas=4)
    ctl = FleetController(sched, config=cfg, metrics=sched.metrics)
    for _ in range(3):
        reps[0].engine.batcher.submit(Request([1]))
    assert ctl.poll() == ["scale_up"]
    assert reps[1].state == "healthy"  # spare revived via mark_alive
    reps[0].engine.batcher.drain()
    assert ctl.poll() == []            # idle hysteresis: 1 of 2 polls
    assert ctl.poll() == ["scale_down"]
    assert sum(1 for r in sched.fleet() if r.state == "healthy") == 1
    assert ctl.stats()["scale_events"]["scale_up"] == 1
    assert ctl.stats()["scale_events"]["scale_down"] == 1


def test_controller_propagates_brownout_to_batchers_and_engines():
    sched, reps = _fleet(2)
    cfg = _cfg(up_polls=1, brownout_polls=1, brownout_clear_polls=1,
               queue_high=1.0, max_replicas=2)  # at envelope, no spares
    ctl = FleetController(sched, config=cfg, metrics=sched.metrics)
    for _ in range(4):
        reps[0].engine.batcher.submit(Request([1]))
    # up_polls = brownout_polls = 1: every stuck poll climbs one rung.
    assert ctl.poll() == ["brownout_up"]
    assert ctl.poll() == ["brownout_up"]    # rung 2: max_new cap engages
    for r in reps:
        assert r.engine.batcher.brownout_level == 2
        assert r.engine.batcher.brownout_max_new == cfg.brownout_max_new
        assert r.engine.brownout_level == 2
    assert sched.metrics.snapshot()["brownout_level"] == 2
    with pytest.raises(QueueFullError):
        reps[0].engine.batcher.submit(Request([9], qos="throughput"))
    reps[0].engine.batcher.drain()
    assert ctl.poll() == ["brownout_down"]
    assert ctl.poll() == ["brownout_down"]
    for r in reps:
        assert r.engine.batcher.brownout_level == 0
        assert r.engine.batcher.brownout_max_new == 0
    assert ctl.stats()["brownout_level"] == 0
    assert ctl.stats()["brownout_seconds"] >= 0.0
    events = sched.metrics.snapshot()["ctl_events"]
    assert events["brownout_up"] == 2 and events["brownout_down"] == 2


def test_controller_thread_lifecycle_and_poll_error_recovery():
    sched, _ = _fleet(1)
    cfg = _cfg(poll_s=0.01)
    ctl = FleetController(sched, config=cfg, metrics=sched.metrics)
    broken = {"n": 0}
    real_snapshot = ctl.snapshot

    def flaky_snapshot():
        broken["n"] += 1
        if broken["n"] == 1:
            raise RuntimeError("injected snapshot failure")
        return real_snapshot()

    ctl.snapshot = flaky_snapshot
    ctl.start()
    try:
        deadline = time.monotonic() + 10
        while broken["n"] < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert broken["n"] >= 3, "poll loop died after one error"
    finally:
        ctl.stop()
    assert ctl._thread is None
    assert sched.metrics.snapshot()["ctl_events"]["poll_error"] == 1
