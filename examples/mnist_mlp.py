"""MNIST-style end-to-end example — the framework's "minimum slice".

Mirrors the reference's first driver config (examples/tensorflow2/
tensorflow2_keras_mnist.py): init, shard the data, wrap the optimizer in
DistributedOptimizer, broadcast initial parameters from rank 0, train, and
let only rank 0 report/checkpoint.  Uses synthetic MNIST-shaped data (the
benchmark harnesses in the reference are synthetic too; this box has no
network egress).

Run (emulated 8-rank slice):
    HVD_TPU_EMULATE_RANKS=8 python examples/mnist_mlp.py
Run (real chip):
    python examples/mnist_mlp.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("HVD_TPU_EMULATE_RANKS"):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def mlp_init(rng, sizes=(784, 128, 10)):
    params = []
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        k1, rng = jax.random.split(rng)
        params.append({
            "w": jax.random.normal(k1, (m, n), jnp.float32) * (2.0 / m) ** 0.5,
            "b": jnp.zeros((n,), jnp.float32),
        })
    return params


def mlp_apply(params, x):
    for layer in params[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    last = params[-1]
    return x @ last["w"] + last["b"]


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 784).astype(np.float32)
    w_true = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w_true + 0.1 * rng.randn(n, 10), axis=1)
    return x, y.astype(np.int32)


def main():
    hvd.init()
    nslots = hvd.num_slots()
    print(f"rank={hvd.rank()} size={hvd.size()} slots={nslots}")

    params = mlp_init(jax.random.PRNGKey(42 + hvd.rank()))
    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    opt_state = opt.init(params)

    # Rank 0's initial parameters reach everyone (SURVEY.md §5.4 convention;
    # examples/pytorch/pytorch_imagenet_resnet50.py broadcast pattern).
    # Under SPMD all slots share `params` already, but the call is kept for
    # parity and correctness in multi-controller mode.
    params = hvd.broadcast_variables(params, root_rank=0)

    x, y = synthetic_mnist()
    per_slot = x.shape[0] // nslots

    def local_step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = mlp_apply(p, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Metric averaging (keras MetricAverageCallback analog).
        loss = hvd.allreduce(loss, op=hvd.Average)
        return params, opt_state, loss

    step = hvd.parallel.shard_step(
        lambda p, s, xb, yb: local_step(p, s, xb, yb),
        in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P()))

    losses = []
    bs = 512
    for epoch in range(3):
        for i in range(0, x.shape[0] - bs + 1, bs):
            xb = jnp.asarray(x[i:i + bs])
            yb = jnp.asarray(y[i:i + bs])
            params, opt_state, loss = step(params, opt_state, xb, yb)
        losses.append(float(loss))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(loss):.4f}")

    assert losses[-1] < losses[0], "loss did not decrease"
    if hvd.rank() == 0:
        print("OK: distributed MNIST training converged "
              f"({losses[0]:.3f} -> {losses[-1]:.3f})")
    return losses


if __name__ == "__main__":
    main()
