"""Spark Estimator example: train an MNIST-scale MLP from a DataFrame.

Reference analog: examples/spark/keras/keras_spark_mnist.py — load data
into a DataFrame, hand it to the estimator, get a Transformer back.

Runs with or without pyspark: a SparkSession trains on barrier tasks; no
Spark (this image) trains through the local multi-process launcher with a
pandas DataFrame — the Store/Parquet/shard path is identical.

Usage::

    python examples/spark_estimator_mnist.py --num-proc 2 --epochs 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--num-proc", type=int, default=2)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--store", default="/tmp/hvd_tpu_estimator_store")
    p.add_argument("--platform", default=None,
                   help="force a jax platform in workers (tests use cpu)")
    args = p.parse_args(argv)

    if args.platform:
        # Applies to this (caller) process too: transform/predict run here,
        # and the first device use would otherwise initialize the default
        # platform.
        import jax
        jax.config.update("jax_platforms", args.platform)

    import numpy as np
    import optax
    import pandas as pd

    from horovod_tpu.models import create_mlp
    from horovod_tpu.spark import HorovodTpuEstimator, LocalStore

    # Synthetic MNIST-shaped data (the reference example downloads MNIST;
    # this environment has no egress).
    rng = np.random.RandomState(0)
    X = rng.rand(2048, 64).astype(np.float32)
    w = rng.rand(64, 10)
    y = np.argmax(X @ w, axis=1)
    df = pd.DataFrame({"features": [list(map(float, r)) for r in X],
                       "y": [int(v) for v in y]})

    est = HorovodTpuEstimator(
        model=create_mlp((128, 10)),
        optimizer=optax.adam(1e-3),
        loss="sparse_categorical_crossentropy",
        feature_cols=["features"], label_cols=["y"],
        batch_size=args.batch_size, epochs=args.epochs, validation=0.1,
        store=LocalStore(args.store), num_proc=args.num_proc,
        worker_platform=args.platform)
    model = est.fit(df)
    print("history:", est.history)
    out = model.transform(df.head(16))
    pred = np.argmax(np.stack(out["y__output"].to_numpy()), axis=1)
    acc = float(np.mean(pred == df.head(16)["y"].to_numpy()))
    print(f"train-head accuracy after {args.epochs} epochs: {acc:.2f}")
    return est.history


if __name__ == "__main__":
    main()
