"""Elastic ResNet training under worker churn.

BASELINE.json config 5: "Elastic ResNet-50 (examples/elastic, preemptible
TPU-VM worker churn)".  Demonstrates the full elastic contract: TpuState
commit/restore, the retry decorator, and checkpoint save/restore via the
rank-0 convention.  Membership churn is driven by the elastic CLI
(--host-discovery-script); this script is churn-agnostic — it just commits
at safe points and keeps training.

Run under the elastic launcher:
    horovodrun --min-np 1 --max-np 8 --host-discovery-script ./discover.sh \
        python examples/elastic_resnet.py
Or standalone (emulated slice):
    HVD_TPU_EMULATE_RANKS=8 python examples/elastic_resnet.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("HVD_TPU_EMULATE_RANKS"):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import create_resnet50


def main():
    hvd.init()
    nslots = hvd.num_slots()
    model = create_resnet50(num_classes=10, dtype=jnp.float32, sync_bn=True)

    def make_data():
        # Batch is a function of the CURRENT world: rebuilt on every elastic
        # resize (a fixed batch would stop dividing over the new mesh).
        batch = 4 * hvd.num_slots()
        images = jnp.asarray(np.random.RandomState(0)
                             .rand(batch, 32, 32, 3).astype(np.float32))
        labels = jnp.asarray(np.random.RandomState(1)
                             .randint(0, 10, (batch,)))
        return images, labels

    images, labels = make_data()
    variables = model.init(jax.random.PRNGKey(0), images[:1], train=False)
    opt = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9))
    state = hvd.elastic.TpuState(
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=opt.init(variables["params"]),
        batch=0)

    def local_step(params, batch_stats, opt_state, xb, yb):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, xb, train=True,
                mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean(), mut["batch_stats"]
        (loss, nbs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        u, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, u), nbs, opt_state,
                hvd.allreduce(loss, op=hvd.Average))

    def make_step():
        # Rebuilt by the reset callback: the mesh (and compiled program)
        # change when the world resizes.
        return hvd.parallel.shard_step(
            local_step,
            in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
            out_specs=(P(), P(), P(), P()))

    holder = {"step": make_step(), "data": (images, labels)}

    def on_reset():
        holder["step"] = make_step()
        holder["data"] = make_data()

    state.register_reset_callbacks([on_reset])

    @hvd.elastic.run
    def train(state):
        loss = jnp.zeros(())  # defined even if re-entered with batch == 60
        while state.batch < 60:
            xb, yb = holder["data"]
            state.params, state.batch_stats, state.opt_state, loss = \
                holder["step"](state.params, state.batch_stats,
                               state.opt_state, xb, yb)
            state.batch += 1
            if state.batch % 10 == 0:
                state.commit()
        return float(loss)

    final = train(state)
    # save() must be called from EVERY rank: rank 0 writes, the rest no-op
    # into the completion barrier.
    hvd.checkpoint.save("/tmp/elastic_resnet_ckpt",
                        {"params": state.params, "batch": state.batch})
    if hvd.rank() == 0:
        print(f"elastic training finished: batches={state.batch} "
              f"loss={final:.4f}")
        print("checkpoint saved (rank-0 convention)")
    return final


if __name__ == "__main__":
    main()
