"""Elastic training on a Ray cluster.

Reference analog: examples/ray/ray_elastic.py (elastic_v2 executor).

The Ray autoscaler adding/removing nodes drives elastic scale-up/down:
RayHostDiscovery turns alive-node resources into the host:slots view the
ElasticDriver consumes, and each assigned slot runs as a Ray actor.

Requires a running Ray cluster (`ray.init(...)` first)::

    python examples/ray_elastic_example.py --min-workers 1 --max-workers 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def train_fn():
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    state = hvd.elastic.TpuState(params={"w": jnp.zeros((4,))}, step=0)

    @hvd.elastic.run
    def train(state):
        while state.step < 50:
            g = hvd.allreduce(jnp.ones((4,)), op=hvd.Average, name="g")
            state.params = {"w": state.params["w"] + g}
            state.step += 1
            state.commit()
        return float(state.params["w"][0])

    return train(state)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--min-workers", type=int, default=1)
    p.add_argument("--max-workers", type=int, default=4)
    p.add_argument("--cpus-per-worker", type=int, default=1)
    args = p.parse_args(argv)

    import ray

    from horovod_tpu.ray import ElasticRayExecutor

    if not ray.is_initialized():
        ray.init()
    executor = ElasticRayExecutor(
        min_workers=args.min_workers, max_workers=args.max_workers,
        cpus_per_worker=args.cpus_per_worker)
    executor.start()
    try:
        results = executor.run(train_fn)
        print("per-rank results:", results)
    finally:
        executor.shutdown()


if __name__ == "__main__":
    main()
