"""GPT-2 language modeling with Adasum reduction.

BASELINE.json config 4: "GPT-2 medium with Adasum (examples/adasum, torch
backend)".  Adasum (ops/adasum.py — butterfly ppermute tree with the
orthogonal-projection-corrected pairwise combine, adasum.h:396-409) adapts
between summing and averaging per tensor, letting the learning rate stay
fixed as the world grows.

Run small (emulated 8-rank CPU slice):
    HVD_TPU_EMULATE_RANKS=8 python examples/gpt2_adasum.py --size tiny
GPT-2 medium on the chip:
    python examples/gpt2_adasum.py --size medium --steps 10
"""

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("HVD_TPU_EMULATE_RANKS"):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import Transformer, TransformerConfig, create_gpt2, \
    lm_loss

TINY = TransformerConfig(vocab_size=512, num_layers=2, num_heads=8,
                         d_model=128, d_ff=256, max_len=128, causal=True,
                         dtype=jnp.float32)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="tiny",
                    choices=["tiny", "small", "medium", "large"])
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-per-slot", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=128)
    args = ap.parse_args(argv)

    hvd.init()
    nslots = hvd.num_slots()
    # scan_layers (factory default): ~num_layers x faster compile — the
    # >10 min remote-compile that blocked on-chip GPT-2 captures in rounds
    # 2-4.  Adasum's per-tensor coefficient granularity (adasum.h:396-409)
    # survives the stacked [L, ...] layout via per_layer_stacked below:
    # the scanned blocks get one coefficient pair PER LAYER SLICE, exactly
    # what the unrolled layout computed.
    model = Transformer(TINY) if args.size == "tiny" else \
        create_gpt2(args.size, remat=True)
    cfg = model.cfg
    batch = args.batch_per_slot * nslots
    seq_len = min(args.seq_len, cfg.max_len)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, cfg.vocab_size, size=(batch, seq_len))
        .astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), tokens[:1])
    params = hvd.broadcast_variables(params, root_rank=0)
    # Adasum path: reduce post-optimizer deltas (the reference's
    # _DistributedAdasumOptimizer contract, torch/optimizer.py:345).
    opt = optax.sgd(0.05)
    opt_state = opt.init(params)

    def _stacked_layer_leaf(path):
        # The scanned model's "blocks" subtree stacks per-layer params on
        # axis 0; per-slice Adasum keeps reference granularity there.
        return any(getattr(p, "key", None) == "blocks" for p in path)

    def local_step(params, opt_state, toks):
        def loss_fn(p):
            logits = model.apply(p, toks)
            return lm_loss(logits[:, :-1], toks[:, 1:])
        # LOCAL grads: Adasum adapts from per-rank gradient divergence.
        loss, grads = hvd.local_value_and_grad(loss_fn)(params)
        new_params, opt_state2 = hvd.adasum_delta_step(
            opt, params, grads, opt_state,
            per_layer_stacked=_stacked_layer_leaf if cfg.scan_layers
            else None)
        return new_params, opt_state2, hvd.allreduce(loss, op=hvd.Average)

    step = hvd.parallel.shard_step(
        local_step, in_specs=(P(), P(), P("hvd")),
        out_specs=(P(), P(), P()), donate_argnums=(0, 1),
        check_vma=False)  # Adasum butterfly output: equal but typed varying

    losses = []
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
        if i == 1:
            t0 = time.perf_counter()
    dt = max(time.perf_counter() - t0, 1e-9)
    samples_s = batch * max(args.steps - 2, 0) / dt if args.steps > 2 else 0.0
    if hvd.rank() == 0:
        print(f"lm loss: {losses[0]:.4f} -> {losses[-1]:.4f}  "
              f"({samples_s:.1f} samples/sec, Adasum)")
    if args.steps > 3:
        assert losses[-1] < losses[0], "loss did not decrease"
    return losses, samples_s


if __name__ == "__main__":
    main()
