"""User-facing synthetic training benchmark.

Reference analog: examples/pytorch/pytorch_synthetic_benchmark.py and
examples/tensorflow2/tensorflow2_synthetic_benchmark.py:25-80 — the
reference's headline harness: a standard model on synthetic data, full
training steps through DistributedOptimizer, images/sec printed.

(The driver-facing single-JSON-line variant lives at the repo root as
bench.py; this is the argparse'd example users run.)

Usage::

    python examples/synthetic_benchmark.py --model resnet50 --batch-size 128
    python examples/synthetic_benchmark.py --model mlp --num-iters 50
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101", "mlp"])
    p.add_argument("--batch-size", type=int, default=128,
                   help="per-slot batch size")
    p.add_argument("--num-warmup-batches", type=int, default=5)
    p.add_argument("--num-iters", type=int, default=30)
    p.add_argument("--no-sync-bn", action="store_true")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd

    hvd.init()
    n = hvd.num_slots()
    batch = args.batch_size * n

    if args.model == "mlp":
        from horovod_tpu.models import create_mlp
        model = create_mlp((1024, 1024, 1000))
        images = jnp.asarray(
            np.random.RandomState(0).rand(batch, 784).astype(np.float32))
    else:
        from horovod_tpu.models import ResNet50, ResNet101
        cls = ResNet50 if args.model == "resnet50" else ResNet101
        model = cls(num_classes=1000, dtype=jnp.bfloat16,
                    axis_name=None if args.no_sync_bn else "hvd")
        images = jnp.asarray(
            np.random.RandomState(0).rand(batch, 224, 224, 3)
            .astype(np.float32))
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(batch,)))

    has_bn = args.model != "mlp"
    variables = model.init(jax.random.PRNGKey(0), images[:2],
                           **({"train": False} if has_bn else {}))
    params = variables["params"] if "params" in variables else variables
    batch_stats = variables.get("batch_stats") if has_bn else None
    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    opt_state = opt.init(params)

    def local_step(params, batch_stats, opt_state, xb, yb):
        def loss_fn(p):
            if has_bn:
                logits, mut = model.apply(
                    {"params": p, "batch_stats": batch_stats}, xb,
                    train=True, mutable=["batch_stats"])
                new_stats = mut["batch_stats"]
            else:
                logits, new_stats = model.apply({"params": p}, xb), None
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()
            return loss, new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        loss = hvd.allreduce(loss, op=hvd.Average)  # metric averaging
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    step = hvd.shard_step(
        local_step,
        in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P(), P()),
        donate_argnums=(0, 1, 2))

    for _ in range(args.num_warmup_batches):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(loss)  # host sync (reliable through remote-execution PJRT)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(loss)
    dt = time.perf_counter() - t0
    img_s = batch * args.num_iters / dt
    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/slot, "
              f"{n} slot(s)")
        print(f"Img/sec total: {img_s:.1f}  (per slot: {img_s / n:.1f})")
    return img_s


if __name__ == "__main__":
    main()
