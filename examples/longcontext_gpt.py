"""Long-context GPT training with ring attention — beyond-Horovod capability.

The reference has no sequence parallelism (SURVEY.md §5.8); this example
trains a small causal LM on sequences far longer than one device's
attention memory by sharding the SEQUENCE across the mesh: each shard holds
S/n tokens, ring attention (striped layout for balanced causal work)
computes exact attention over the full context, and gradients synchronize
through the same DistributedOptimizer as any data-parallel job.

Run (8-shard emulated slice, 2048-token context):
    HVD_TPU_EMULATE_RANKS=8 python examples/longcontext_gpt.py
Longer contexts: --seq-len 8192 (memory per shard stays S/n).
"""

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("HVD_TPU_EMULATE_RANKS"):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import Transformer, TransformerConfig, lm_loss
from horovod_tpu.parallel.ring import stripe_sequence


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=1)
    args = ap.parse_args(argv)

    hvd.init()
    n = hvd.num_slots()
    S = args.seq_len
    assert S % n == 0, f"--seq-len must divide by {n} shards"

    cfg = TransformerConfig(vocab_size=512, num_layers=2, num_heads=8,
                            d_model=128, d_ff=256, max_len=S, causal=True,
                            dtype=jnp.float32, seq_parallel="ring_striped")
    model = Transformer(cfg)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size, (args.batch, S)).astype(np.int32)
    # Striped layout: shard i holds tokens i, i+n, i+2n, ... (balanced
    # causal work per ring hop).  Targets stripe identically; positions come
    # from striped_positions inside the sharded step.
    tokens_striped = jnp.asarray(stripe_sequence(jnp.asarray(tokens), n))
    targets = np.roll(tokens, -1, axis=1)  # next-token, global order
    targets_striped = jnp.asarray(stripe_sequence(jnp.asarray(targets), n))

    # init with the dense twin (same params; attention backend differs)
    params = Transformer(dataclasses.replace(cfg, seq_parallel=None)).init(
        jax.random.PRNGKey(0), tokens_striped[:, :8])
    params = hvd.broadcast_variables(params, root_rank=0)
    opt = hvd.DistributedOptimizer(optax.adam(3e-3))
    opt_state = opt.init(params)

    def local_step(params, opt_state, toks, tgts):
        def loss_fn(p):
            logits = model.apply(p, toks)  # striped positions are automatic
            return lm_loss(logits, tgts)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, hvd.allreduce(loss, op=hvd.Average)

    step = hvd.parallel.shard_step(
        local_step, in_specs=(P(), P(), P(None, "hvd"), P(None, "hvd")),
        out_specs=(P(), P(), P()))

    losses = []
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens_striped,
                                       targets_striped)
        losses.append(float(loss))
        if i == 0:
            t0 = time.perf_counter()
    dt = time.perf_counter() - t0
    if hvd.rank() == 0:
        tok_s = args.batch * S * max(args.steps - 1, 1) / max(dt, 1e-9)
        print(f"long-context lm loss: {losses[0]:.4f} -> {losses[-1]:.4f} "
              f"(seq={S} over {n} shards, {tok_s:.0f} tok/s)")
    assert losses[-1] < losses[0], "loss did not decrease"
    return losses


if __name__ == "__main__":
    main()
