"""BERT pretraining with DistributedOptimizer + gradient accumulation.

BASELINE.json config 3: "BERT-large pretraining (PyTorch backend,
DistributedOptimizer + grad accumulation)" — here TPU-native: bf16 MXU
matmuls, masked-LM objective on synthetic data, grad accumulation via
``backward_passes_per_step`` (torch/optimizer.py:126 semantics), sequence
sharded optionally with ring attention for long contexts.

Run small (emulated 8-rank CPU slice):
    HVD_TPU_EMULATE_RANKS=8 python examples/bert_pretraining.py --size tiny
Run BERT-large on the chip:
    python examples/bert_pretraining.py --size large --steps 10
"""

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("HVD_TPU_EMULATE_RANKS"):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import (BERT_BASE, BERT_LARGE, Transformer,
                                TransformerConfig, lm_loss)

TINY = TransformerConfig(vocab_size=1024, num_layers=2, num_heads=8,
                         d_model=128, d_ff=256, max_len=128, causal=False,
                         dtype=jnp.float32)

MASK_ID = 103  # [MASK] in the BERT vocab


def mlm_batch(rng, batch, seq_len, vocab, mask_rate=0.15):
    tokens = rng.randint(5, vocab, size=(batch, seq_len)).astype(np.int32)
    mask = rng.rand(batch, seq_len) < mask_rate
    inputs = tokens.copy()
    inputs[mask] = MASK_ID
    return (jnp.asarray(inputs), jnp.asarray(tokens),
            jnp.asarray(mask.astype(np.float32)))


def mlm_batch_fixed_positions(rng, batch, seq_len, vocab, num_positions):
    """Exactly ``num_positions`` masked slots per sequence (standard BERT
    max_predictions_per_seq).  Returns (inputs, positions [B,K], labels
    [B,K]); the LM head runs only at the gathered positions."""
    tokens = rng.randint(5, vocab, size=(batch, seq_len)).astype(np.int32)
    positions = np.stack([
        np.sort(rng.choice(seq_len, size=num_positions, replace=False))
        for _ in range(batch)]).astype(np.int32)
    labels = np.take_along_axis(tokens, positions, axis=1)
    inputs = tokens.copy()
    np.put_along_axis(inputs, positions, MASK_ID, axis=1)
    return jnp.asarray(inputs), jnp.asarray(positions), jnp.asarray(labels)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="tiny", choices=["tiny", "base", "large"])
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-per-slot", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--accum", type=int, default=2,
                    help="backward_passes_per_step (grad accumulation)")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize blocks (jax.checkpoint); only pays "
                         "off when activations would not fit HBM (long "
                         "seq / large batch) — at seq 128 it costs ~1/3 "
                         "extra forward FLOPs for nothing")
    ap.add_argument("--attention", default="auto",
                    choices=["auto", "dense", "flash"],
                    help="'flash' = Pallas kernel (fwd+bwd); 'auto' picks "
                         "flash on TPU, dense elsewhere")
    ap.add_argument("--mlm-positions", type=int, default=0,
                    help="if >0, generate exactly this many masked "
                         "positions per sequence and apply the LM head "
                         "only at them (standard BERT "
                         "max_predictions_per_seq; the head over all "
                         f"positions wastes ~6x its FLOPs at 15%% masking)")
    args = ap.parse_args(argv)

    hvd.init()
    nslots = hvd.num_slots()
    attn = args.attention
    if attn == "auto":
        # flash only when the kernels actually COMPILE here, for THIS
        # model's shape/dtype (a Mosaic rejection must degrade to dense,
        # not kill the bench run — parallel/flash.py flash_supported).
        from horovod_tpu.parallel.flash import flash_supported
        probe_cfg = TINY if args.size == "tiny" else \
            {"base": BERT_BASE, "large": BERT_LARGE}[args.size]
        attn = "flash" if (
            jax.default_backend() == "tpu"
            and flash_supported(
                dtype=str(jnp.dtype(probe_cfg.dtype)),
                head_dim=probe_cfg.d_model // probe_cfg.num_heads,
                seq_len=args.seq_len, causal=probe_cfg.causal)
        ) else "dense"
    attn_impl = "flash" if attn == "flash" else None
    if args.size == "tiny":
        cfg = dataclasses.replace(TINY, attention_impl=attn_impl)
    else:
        cfg = {"base": BERT_BASE, "large": BERT_LARGE}[args.size]
        # scan_layers: ~num_layers x faster compile at identical numerics
        # (BERT-large's ~7 min remote compile was the bench-window risk).
        cfg = dataclasses.replace(
            cfg, max_len=args.seq_len, remat=args.remat,
            attention_impl=attn_impl, scan_layers=True)
    model = Transformer(cfg)
    batch = args.batch_per_slot * nslots
    seq_len = min(args.seq_len, cfg.max_len)

    rng = np.random.RandomState(hvd.rank())
    if args.mlm_positions:
        inputs, positions, labels = mlm_batch_fixed_positions(
            rng, batch, seq_len, cfg.vocab_size, args.mlm_positions)
        targets, mask = positions, labels  # ride the same step signature
    else:
        inputs, targets, mask = mlm_batch(rng, batch, seq_len,
                                          cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), inputs[:1])
    params = hvd.broadcast_variables(params, root_rank=0)
    opt = hvd.DistributedOptimizer(
        optax.adamw(1e-4), backward_passes_per_step=args.accum,
        compression=hvd.Compression.none)
    opt_state = opt.init(params)

    def local_step(params, opt_state, inp, tgt, msk):
        def loss_fn(p):
            if args.mlm_positions:
                # tgt = positions [B,K], msk = labels [B,K]
                logits = model.apply(p, inp, predict_positions=tgt)
                return lm_loss(logits, msk.astype(jnp.int32))
            logits = model.apply(p, inp)
            return lm_loss(logits, tgt, msk)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        params2 = optax.apply_updates(params, updates)
        return params2, opt_state2, hvd.allreduce(loss, op=hvd.Average)

    step = hvd.parallel.shard_step(
        local_step, in_specs=(P(), P(), P("hvd"), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P()), donate_argnums=(0, 1),
        # Pallas *interpreter* (flash off-TPU) inlines the kernel, mixing
        # invariant loop indices with varying data; the compiled TPU path
        # needs no escape hatch (parallel/flash.py docstring).
        check_vma=not (attn == "flash"
                       and jax.default_backend() != "tpu"))

    # Keep per-step losses ON DEVICE: a float() per step is a host
    # round-trip that serializes dispatch (catastrophic through a remote
    # PJRT transport); fetch the whole trace once at the end.
    losses_dev = []
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, inputs, targets,
                                       mask)
        losses_dev.append(loss)
        if i == 1:
            float(loss)  # barrier after compile+first step
            t0 = time.perf_counter()
    losses = [float(l) for l in jax.device_get(losses_dev)]  # ONE transfer
    dt = max(time.perf_counter() - t0, 1e-9)
    samples_s = batch * max(args.steps - 2, 0) / dt if args.steps > 2 else 0.0
    if hvd.rank() == 0:
        print(f"mlm loss: {losses[0]:.4f} -> {losses[-1]:.4f}  "
              f"({samples_s:.1f} samples/sec, accum={args.accum})")
    if args.steps > 3:
        assert losses[-1] < losses[0], "loss did not decrease"
    return losses, samples_s


if __name__ == "__main__":
    main()
