#!/usr/bin/env python
"""Control-plane scale benchmark: eager negotiation throughput vs np.

Measures the pure coordination cost of the eager path — the two KV
round-trips per NEW tensor signature and the one stream-publish per CACHED
dispatch (ops/negotiation.py cost model) — against a real KVStoreServer
with real worker processes, no collective execution attached.  This is the
analog of the reference's controller cycle cost, which its bitvector cache
fast path exists to amortize (controller.cc:845 CoordinateCacheAndState).

Usage:  python tools/control_plane_bench.py [--np 8 16] [--names 40]
        [--repeats 25] [--json artifacts/control_plane.json]

Per np it reports:
  - new-signature negotiations/sec (whole-world rate) + p50/p99 latency
  - cached dispatches/sec per rank + p50/p99 latency
  - KV server request load (requests/sec observed by the server)
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
from horovod_tpu.config import Config
from horovod_tpu.ops.negotiation import Negotiator

rank = int(os.environ["BENCH_RANK"]); size = int(os.environ["BENCH_SIZE"])
names = int(os.environ["BENCH_NAMES"]); reps = int(os.environ["BENCH_REPEATS"])
cfg = Config.from_env()
neg = Negotiator(rank, size, cfg)
assert neg.enabled, "negotiator disabled (no rendezvous env)"

# Phase A: new signatures (2 KV round-trips + coordinator validation each).
lat_new = []
for i in range(names):
    t0 = time.perf_counter()
    neg.negotiate(f"grad.{{i}}", "allreduce", "float32", (128, 128), op=2)
    lat_new.append(time.perf_counter() - t0)

# Phase B: cached dispatches (response-cache HIT -> one stream publish).
lat_hit = []
for _ in range(reps):
    for i in range(names):
        t0 = time.perf_counter()
        neg.negotiate(f"grad.{{i}}", "allreduce", "float32", (128, 128), op=2)
        lat_hit.append(time.perf_counter() - t0)

print("RESULT " + json.dumps({{"rank": rank, "new": lat_new,
                               "hit": lat_hit}}), flush=True)
"""


def pct(xs, p):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(p / 100 * len(xs)))]


def run_scale(np_, names, repeats):
    from horovod_tpu.runner.http_server import KVStoreServer
    srv = KVStoreServer()
    port = srv.start(0)
    script = WORKER.format(repo=REPO)
    t_start = time.perf_counter()
    procs = []
    for r in range(np_):
        env = dict(os.environ,
                   BENCH_RANK=str(r), BENCH_SIZE=str(np_),
                   BENCH_NAMES=str(names), BENCH_REPEATS=str(repeats),
                   HOROVOD_GLOO_RENDEZVOUS_ADDR="127.0.0.1",
                   HOROVOD_GLOO_RENDEZVOUS_PORT=str(port))
        procs.append(subprocess.Popen([sys.executable, "-c", script],
                                      env=env, stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE, text=True))
    results = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        if p.returncode != 0:
            raise SystemExit(f"worker failed:\n{err[-2000:]}")
        for line in out.splitlines():
            if line.startswith("RESULT "):
                results.append(json.loads(line[len("RESULT "):]))
    wall = time.perf_counter() - t_start
    srv.stop()

    new_all = [x for r in results for x in r["new"]]
    hit_all = [x for r in results for x in r["hit"]]
    # Whole-world negotiation rate: every rank negotiates the same `names`
    # signatures; the world completes `names` negotiations in the time the
    # slowest rank takes over phase A.
    new_time_per_rank = [sum(r["new"]) for r in results]
    hit_time_per_rank = [sum(r["hit"]) for r in results]
    return {
        "np": np_,
        "names": names,
        "repeats": repeats,
        "negotiations_per_sec_world": names / max(new_time_per_rank),
        "new_p50_ms": pct(new_all, 50) * 1e3,
        "new_p99_ms": pct(new_all, 99) * 1e3,
        "cached_dispatch_per_sec_rank":
            names * repeats / max(hit_time_per_rank),
        "hit_p50_ms": pct(hit_all, 50) * 1e3,
        "hit_p99_ms": pct(hit_all, 99) * 1e3,
        "wall_s": wall,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, nargs="+", default=[2, 8, 16])
    ap.add_argument("--names", type=int, default=40)
    ap.add_argument("--repeats", type=int, default=25)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    rows = []
    for n in args.np:
        row = run_scale(n, args.names, args.repeats)
        rows.append(row)
        print(json.dumps(row))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
