#!/bin/bash
# Opportunistic TPU bench capture: probe the relay on a loop; the moment it
# answers, run the full bench battery (ResNet fast-stem + naive-stem, BERT
# dense vs flash attention) and persist every capture via bench.py's
# last-good mechanism.  Logs to artifacts/opportunistic_capture.log.
#
# Motivated by VERDICT r3 Missing #1: three rounds of driver-time relay
# outages zeroed the official perf record; captures must happen whenever the
# relay is up, not only at driver time.
set -u
cd "$(dirname "$0")/.."
LOG=artifacts/opportunistic_capture.log
mkdir -p artifacts
echo "=== opportunistic capture watcher started $(date -u +%FT%TZ) ===" >> "$LOG"

probe() {
    timeout 90 python -c "import jax; assert jax.devices()" >/dev/null 2>&1
}

while true; do
    # A test run owns the box's one core; a hung jax-import probe would
    # steal CPU from subprocess-heavy e2e tests and flake them.  Detect a
    # real pytest invocation: a "pytest" token (bare or path-suffixed)
    # within a command line's FIRST TEN tokens covers `pytest ...`,
    # `python -m pytest ...`, `/venv/bin/pytest`, and wrapper-prefixed
    # forms (`timeout N`, `nice -n 10`, `env A=B`), while NOT matching
    # processes that merely quote the word DEEP in an argument (a session
    # wrapper's embedded prompt — "pytest" hundreds of tokens in —
    # silenced this watcher entirely with a bare `pgrep -f pytest`).
    # Tradeoff: a wrapper quoting "pytest" within its first ten tokens
    # would pause probing; none such runs here.
    # OPP_TEST_MODE=1 (tests/test_opportunistic_watcher.py) bypasses the
    # pytest pause — the test itself runs under pytest, which would
    # otherwise park this loop forever.
    if [ "${OPP_TEST_MODE:-0}" != "1" ] && \
       ps -eo args= | awk '{ for (i = 1; i <= 10 && i <= NF; i++)
                                 if ($i ~ /(^|\/)pytest$/) f = 1 }
                           END { exit !f }'; then
        sleep 60
        continue
    fi
    if probe; then
        echo "--- relay up $(date -u +%FT%TZ); running battery ---" >> "$LOG"
        # 1. ResNet-50 fast stem (the driver's default invocation).
        # bench.py emits the last-good record (stale:true) up front on
        # EVERY run, then prints a fresh line on success — so success is
        # rc==0 AND a non-stale LAST JSON line (the stale-only path exits
        # nonzero since the round-5 emit-first rework, but belt+braces).
        OUT=artifacts/capture_resnet_fast.out
        timeout 1200 env BENCH_PROBE_BUDGET_S=120 python bench.py \
            > "$OUT" 2>> "$LOG"
        rc1=$?
        cat "$OUT" >> "$LOG"
        if [ "$rc1" -eq 0 ] && ! python - "$OUT" <<'EOF'
import json, sys
lines = [l for l in open(sys.argv[1]) if l.strip().startswith("{")]
sys.exit(0 if lines and not json.loads(lines[-1]).get("stale") else 1)
EOF
        then
            rc1=99   # stale emission, not a fresh capture: keep looping
        fi
        # 2. ResNet-50 naive stem (for the s2d ablation in PERF_r04.md)
        timeout 1200 env BENCH_PROBE_BUDGET_S=120 BENCH_FAST_STEM=0 \
            HVD_TPU_BENCH_TAG=naive python bench.py \
            >> artifacts/capture_resnet_naive.log 2>&1
        rc2=$?
        # 3. BERT-large dense attention
        timeout 1800 env BENCH_PROBE_BUDGET_S=120 BENCH_MODEL=bert-large \
            BENCH_BERT_ATTN=dense python bench.py \
            >> artifacts/capture_bert_dense.log 2>&1
        rc3=$?
        # 4. BERT-large flash attention (Pallas kernel — first real-TPU run)
        timeout 1800 env BENCH_PROBE_BUDGET_S=120 BENCH_MODEL=bert-large \
            BENCH_BERT_ATTN=flash python bench.py \
            >> artifacts/capture_bert_flash.log 2>&1
        rc4=$?
        # 5. GPT-2 medium + per-layer Adasum (BASELINE config 4; viable
        # since scan_layers cut its compile ~12x)
        timeout 1800 env BENCH_PROBE_BUDGET_S=120 BENCH_MODEL=gpt2-medium \
            python bench.py \
            >> artifacts/capture_gpt2.log 2>&1
        rc5=$?
        echo "--- battery done rc=($rc1,$rc2,$rc3,$rc4,$rc5) $(date -u +%FT%TZ) ---" >> "$LOG"
        if [ "$rc1" -eq 0 ]; then
            echo "=== capture complete; watcher exiting ===" >> "$LOG"
            exit 0
        fi
    else
        echo "probe failed $(date -u +%FT%TZ)" >> "$LOG"
    fi
    # Tests drive exactly one loop iteration; rc=3 means "battery ran but
    # no fresh capture" without sleeping out the 120 s retry.
    [ "${OPP_LOOP_ONCE:-0}" = "1" ] && exit 3
    sleep 120
done
