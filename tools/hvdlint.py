#!/usr/bin/env python
"""hvdlint shim: lint without installing the package.

``python tools/hvdlint.py horovod_tpu examples`` from the repo root is
the single command the verify recipe / CI calls; it exits nonzero on any
unsuppressed finding (same contract as ``python -m horovod_tpu.analysis``
and the ``hvdlint`` console script — see docs/static_analysis.md).
``--race`` passes through to the hvdrace lock-order/thread-lifecycle
analysis (HVD2xx), ``--mem`` to the hvdmem HBM donation analysis
(HVD3xx), and ``--comm`` to the hvdshard sharding/communication
analysis (HVD4xx) — all with the identical exit-code contract;
``--all`` runs every pass over one shared walk and exits with the max.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
